"""ERRSIM fault injection, debug sync, and the forked 3-zone cluster.

Reference: ERRSIM tracepoints (ob_tracepoint_def.h) + ObDebugSync
(share/ob_debug_sync.h); the multi-process replica harness that forks
three observers as three zones (mittest/multi_replica, fork at
env/ob_multi_replica_test_base.cpp:472) and the palf-only bench cluster
(mittest/palf_cluster).
"""

import pytest as _pytest

# multi-device mesh / forked-cluster tests: skipped on a single real chip
pytestmark = _pytest.mark.multidevice


import multiprocessing as mp
import socket
import time

import pytest

from oceanbase_tpu.share.errsim import (

    DEBUG_SYNC,
    ERRSIM,
    InjectedError,
    debug_sync,
    errsim_point,
)


@pytest.fixture(autouse=True)
def _clean_registries():
    yield
    ERRSIM.clear()
    DEBUG_SYNC.deactivate()


# ---- errsim ----------------------------------------------------------------


def test_errsim_arm_fire_count_and_clear():
    ERRSIM.arm("EN_TEST_POINT", count=2)
    with pytest.raises(InjectedError):
        errsim_point("EN_TEST_POINT")
    with pytest.raises(InjectedError):
        errsim_point("EN_TEST_POINT")
    errsim_point("EN_TEST_POINT")  # count exhausted: no-op
    assert ERRSIM.fired("EN_TEST_POINT") == 2
    ERRSIM.arm("EN_TEST_POINT", error=ValueError("custom"))
    with pytest.raises(ValueError, match="custom"):
        errsim_point("EN_TEST_POINT")
    ERRSIM.clear("EN_TEST_POINT")
    errsim_point("EN_TEST_POINT")


def test_errsim_mini_merge_failure_hits_dag_warning_history():
    """An injected mini-merge error must surface in the dag warning
    history and leave the tablet intact for the retry."""
    from oceanbase_tpu.server import Database

    db = Database(n_nodes=3, n_ls=1)
    db.config.set("memstore_limit", 20_000)
    db.config.set("freeze_trigger_ratio", 0.2)
    s = db.session()
    s.sql("create table et (k bigint primary key, v bigint not null)")
    ERRSIM.arm("EN_MINI_MERGE", count=-1)
    for b in range(4):
        s.sql("insert into et values " + ",".join(
            f"({b * 60 + i}, 1)" for i in range(60)))
    assert any(
        w.dag_type == "MINI_MERGE" for w in db.dag_scheduler.warnings
    ), "injected failure did not reach the warning history"
    ERRSIM.clear("EN_MINI_MERGE")
    db.run_maintenance()  # retry succeeds now
    assert s.sql("select count(*) as c from et").rows() == [(240,)]


def test_errsim_commit_failure_rolls_back_cleanly():
    from oceanbase_tpu.server import Database

    db = Database(n_nodes=3, n_ls=1)
    s = db.session()
    s.sql("create table ec (k bigint primary key)")
    # a single injected commit fault is absorbed by the statement retry
    # controller: the INSERT succeeds and the redrive shows up in audit
    ERRSIM.arm("EN_TX_COMMIT", count=1)
    s.sql("insert into ec values (1)")
    assert db.audit.records()[-1].retry_cnt == 1
    assert s.sql("select count(*) as c from ec").rows() == [(1,)]
    # a permanently armed point exhausts the capped retry policy and
    # surfaces raw — the failed attempts must not leak memtable locks
    ERRSIM.arm("EN_TX_COMMIT")
    with pytest.raises(InjectedError):
        s.sql("insert into ec values (2)")
    ERRSIM.clear("EN_TX_COMMIT")
    assert s.sql("select count(*) as c from ec").rows() == [(1,)]
    s.sql("insert into ec values (2)")  # next statement unaffected
    assert s.sql("select count(*) as c from ec").rows() == [(2,)]


def test_debug_sync_interleaves_mid_operation():
    """Park an action at BEFORE_COMMIT: a concurrent reader runs INSIDE
    s1's commit window and must still see the pre-commit snapshot —
    deterministically probing the visibility boundary."""
    from oceanbase_tpu.server import Database

    db = Database(n_nodes=3, n_ls=1)
    s1, s2 = db.session(), db.session()
    s1.sql("create table ds (k bigint primary key, v bigint not null)")
    s1.sql("insert into ds values (1, 0)")

    observed = []

    def observe():
        DEBUG_SYNC.deactivate("BEFORE_COMMIT")
        observed.append(
            s2.sql("select v from ds where k = 1").rows()[0][0]
        )

    s1.sql("begin")
    s1.sql("update ds set v = 1 where k = 1")
    DEBUG_SYNC.activate("BEFORE_COMMIT", observe)
    s1.sql("commit")
    assert observed == [0], "mid-commit read leaked uncommitted state"
    assert s2.sql("select v from ds where k = 1").rows() == [(1,)]


# ---- forked 3-zone palf cluster -------------------------------------------


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _zone_main(zone, ports, conn):
    """One forked zone: a PalfReplica over TcpBus + a control loop."""
    from oceanbase_tpu.log.palf import PalfReplica, Role
    from oceanbase_tpu.log.tcp_transport import TcpBus

    route = {n: ("127.0.0.1", ports[n]) for n in range(3)}
    bus = TcpBus(ports[zone], route, local_nodes={zone})
    rep = PalfReplica(node_id=zone, peers=[0, 1, 2], bus=bus)
    bus.start()
    try:
        while True:
            if conn.poll(0.005):
                cmd, arg = conn.recv()
                if cmd == "role":
                    conn.send((rep.role.name, rep.term))
                elif cmd == "submit":
                    conn.send(rep.submit_log(arg))
                elif cmd == "committed":
                    # skip leadership no-op entries (empty payloads)
                    conn.send([
                        e.payload for e in rep.log[: rep.commit_lsn + 1]
                        if e.payload
                    ])
                elif cmd == "stop":
                    conn.send("ok")
                    return
            rep.tick()
    finally:
        bus.stop()


def test_three_process_palf_cluster():
    """Fork three real processes as three zones: elect, replicate, fail
    over, replicate again (the tier-4 harness)."""
    ctx = mp.get_context("fork")
    ports = _free_ports(3)
    pipes, procs = [], []
    for z in range(3):
        parent, child = ctx.Pipe()
        p = ctx.Process(target=_zone_main, args=(z, ports, child), daemon=True)
        p.start()
        pipes.append(parent)
        procs.append(p)

    def ask(z, cmd, arg=None, timeout=5.0):
        pipes[z].send((cmd, arg))
        if pipes[z].poll(timeout):
            return pipes[z].recv()
        raise TimeoutError(f"zone {z} no reply to {cmd}")

    def wait_leader(exclude=(), timeout=20.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            for z in range(3):
                if z in exclude or not procs[z].is_alive():
                    continue
                role, _term = ask(z, "role")
                if role == "LEADER":
                    return z
            time.sleep(0.05)
        raise TimeoutError("no leader elected")

    try:
        lead = wait_leader()
        # replicate entries through the leader
        for i in range(5):
            lsn = ask(lead, "submit", f"entry-{i}".encode())
            assert lsn is not None
        deadline = time.time() + 10
        follower = next(z for z in range(3) if z != lead)
        while time.time() < deadline:
            got = ask(follower, "committed")
            if len(got) >= 5:
                break
            time.sleep(0.05)
        assert [p for p in got[:5]] == [f"entry-{i}".encode() for i in range(5)]

        # kill the leader PROCESS: the survivors elect a new one
        procs[lead].terminate()
        procs[lead].join(timeout=5)
        lead2 = wait_leader(exclude=(lead,))
        assert lead2 != lead
        assert ask(lead2, "submit", b"after-failover") is not None
        deadline = time.time() + 10
        other = next(z for z in range(3) if z not in (lead, lead2))
        while time.time() < deadline:
            got = ask(other, "committed")
            if b"after-failover" in got:
                break
            time.sleep(0.05)
        assert b"after-failover" in got
    finally:
        for z in range(3):
            if procs[z].is_alive():
                try:
                    ask(z, "stop", timeout=2.0)
                except Exception:
                    pass
                procs[z].terminate()
            procs[z].join(timeout=3)
