"""ANN vector search: exact brute force (ORDER BY vec_l2 LIMIT k = plain
TopN over a matmul-scored key) and the IVF-flat index fast path
(storage/vector_index.py, reference src/storage/vector_index +
src/sql/das/iter ANN iterators)."""

import numpy as np
import pytest

from oceanbase_tpu.core.dtypes import DataType, Field, Schema, TypeKind
from oceanbase_tpu.core.table import Table
from oceanbase_tpu.engine import Session
from oceanbase_tpu.storage.vector_index import (
    build_ivf,
    register_vector_index,
)

I64 = DataType(TypeKind.INT64)


def _vec_table(n=20000, d=32, seed=0):
    rng = np.random.default_rng(seed)
    # clustered data (what embeddings look like): 40 gaussian blobs
    centers = rng.normal(size=(40, d)).astype(np.float32) * 4
    a = rng.integers(0, 40, n)
    x = centers[a] + rng.normal(size=(n, d)).astype(np.float32)
    t = Table(
        "docs",
        Schema((Field("id", I64), Field("emb", DataType.vector(d)))),
        {"id": np.arange(n, dtype=np.int64), "emb": x},
    )
    return {"docs": t}, x, rng


def _qtext(q, k):
    lit = "[" + ",".join(f"{v:.6f}" for v in q) + "]"
    return f"select id from docs order by vec_l2(emb, '{lit}') limit {k}"


def _exact(x, q, k):
    d = ((x - q[None, :]) ** 2).sum(axis=1)
    return np.argsort(d, kind="stable")[:k]


def test_brute_force_exact():
    cat, x, rng = _vec_table(n=5000)
    sess = Session(cat)
    for _ in range(3):
        q = x[rng.integers(0, len(x))] + 0.1
        rs = sess.sql(_qtext(q, 10))
        got = [int(v) for v in rs.columns["id"]]
        want = [int(v) for v in _exact(x, q, 10)]
        assert got == want


def test_ivf_recall_at_10():
    cat, x, rng = _vec_table()
    register_vector_index(cat, "docs", "emb", lists=64, nprobe=8)
    sess = Session(cat)
    hits = total = 0
    first_entry = None
    for i in range(25):
        q = x[rng.integers(0, len(x))] + rng.normal(size=x.shape[1]).astype(
            np.float32) * 0.05
        rs = sess.sql(_qtext(q, 10))
        got = {int(v) for v in rs.columns["id"]}
        want = {int(v) for v in _exact(x, q, 10)}
        hits += len(got & want)
        total += 10
        entry, _ = sess.cached_entry(_qtext(q, 10))
        assert entry.prepared.params.vector_topns, "ANN path did not engage"
        if first_entry is None:
            first_entry = entry
        else:
            # every distinct query vector reuses ONE compiled program
            assert entry is first_entry
    recall = hits / total
    assert recall >= 0.9, f"recall@10 = {recall}"


def test_index_rebuild_after_dml():
    cat, x, rng = _vec_table(n=4000)
    register_vector_index(cat, "docs", "emb", lists=32, nprobe=32)
    sess = Session(cat)
    q = x[7]
    rs = sess.sql(_qtext(q, 1))
    assert int(rs.columns["id"][0]) == 7
    # replace the data in place: id 3 becomes the exact query point
    t = cat["docs"]
    x2 = x.copy()
    x2[3] = q + 100.0  # move 7's twin far away? no: make 3 the nearest
    x2[7] += 50.0
    x2[3] = q
    t.data["emb"] = x2
    sess.executor.invalidate_table("docs")
    rs2 = sess.sql(_qtext(q, 1))
    assert int(rs2.columns["id"][0]) == 3, "stale vector index served"


def test_nprobe_full_is_exact():
    """Probing every list must equal brute force (IVF covers the space)."""
    cat, x, rng = _vec_table(n=3000)
    register_vector_index(cat, "docs", "emb", lists=16, nprobe=16)
    sess = Session(cat)
    for _ in range(3):
        q = rng.normal(size=x.shape[1]).astype(np.float32) * 3
        rs = sess.sql(_qtext(q, 5))
        got = [int(v) for v in rs.columns["id"]]
        want = [int(v) for v in _exact(x, q, 5)]
        assert got == want


def test_ip_and_cosine_metrics():
    """vec_ip / vec_cosine rank by negative inner product and cosine
    DISTANCE (brute-force matmul+top-k; ASC LIMIT k = nearest for every
    metric)."""
    cat, x, rng = _vec_table(n=3000)
    sess = Session(cat)
    q = x[11]
    lit = "[" + ",".join(f"{v:.6f}" for v in q) + "]"
    rs = sess.sql(
        f"select id from docs order by vec_ip(emb, '{lit}') limit 5"
    )
    got = [int(v) for v in rs.columns["id"]]
    want = np.argsort(-(x @ q), kind="stable")[:5]
    assert got == [int(v) for v in want]
    rs = sess.sql(
        f"select id from docs order by vec_cosine(emb, '{lit}') limit 5"
    )
    got = [int(v) for v in rs.columns["id"]]
    sims = (x @ q) / (
        np.linalg.norm(x, axis=1) * np.linalg.norm(q) + 1e-30
    )
    want = np.argsort(-sims, kind="stable")[:5]
    assert got == [int(v) for v in want]


def test_build_ivf_structure():
    x = np.random.default_rng(1).normal(size=(1000, 8)).astype(np.float32)
    idx = build_ivf(x, lists=16)
    assert idx.centroids.shape == (16, 8)
    assert sorted(idx.perm.tolist()) == list(range(1000))
    assert int(idx.lengths.sum()) == 1000
    assert idx.max_list == int(idx.lengths.max())
    # offsets delimit the lists
    ends = idx.offsets + idx.lengths
    assert int(ends.max()) == 1000


def test_server_ddl_and_query():
    from oceanbase_tpu.server.database import Database

    db = Database(n_nodes=1, n_ls=1)
    try:
        s = db.session()
        s.sql("create table docs (id int primary key, emb vector(4))")
        rng = np.random.default_rng(2)
        for i in range(64):
            v = rng.normal(size=4)
            lit = "[" + ",".join(f"{a:.4f}" for a in v) + "]"
            s.sql(f"insert into docs values ({i}, '{lit}')")
        s.sql("create vector index ix on docs (emb) with (lists = 8, nprobe = 8)")
        q = "[0.0,0.0,0.0,0.0]"
        rs = s.sql(
            f"select id from docs order by vec_l2(emb, '{q}') limit 3"
        )
        assert rs.nrows == 3
        # oracle through the freshly read snapshot
        t = db.catalog["docs"]
        x = np.asarray(t.data["emb"], dtype=np.float32)
        want = np.argsort((x * x).sum(axis=1), kind="stable")[:3]
        ids = t.data["id"]
        assert [int(v) for v in rs.columns["id"]] == [
            int(ids[i]) for i in want
        ]
    finally:
        db.close()
