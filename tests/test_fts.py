"""Word-level full-text match (fts_match): the dict-encoded column's
dictionary acts as the inverted index — one token LUT per distinct
value, rows match by code (src/storage/fts redesigned for a
dictionary-columnar engine)."""

import numpy as np
import pytest

from oceanbase_tpu.core.dictionary import Dictionary
from oceanbase_tpu.core.dtypes import DataType, Field, Schema, TypeKind
from oceanbase_tpu.core.table import Table
from oceanbase_tpu.engine import Session


@pytest.fixture()
def sess():
    docs = [
        "quick brown fox", "lazy dog sleeps", "brown dog barks",
        "the fox", "Dog DOG dog",
    ]
    d = Dictionary(sorted(set(docs)), sorted_=True)
    t = Table(
        "doc",
        Schema((
            Field("id", DataType(TypeKind.INT64)),
            Field("body", DataType.varchar()),
        )),
        {"id": np.arange(5, dtype=np.int64),
         "body": d.encode(docs, add=False)},
        {"body": d},
    )
    return Session({"doc": t})


def test_single_token(sess):
    rs = sess.sql("select id from doc where fts_match(body, 'brown') order by id")
    assert [int(r[0]) for r in rs.rows()] == [0, 2]


def test_all_tokens_must_match(sess):
    rs = sess.sql("select id from doc where fts_match(body, 'dog brown')")
    assert [int(r[0]) for r in rs.rows()] == [2]


def test_case_insensitive_and_word_level(sess):
    rs = sess.sql("select id from doc where fts_match(body, 'DOG') order by id")
    assert [int(r[0]) for r in rs.rows()] == [1, 2, 4]
    # word match, not substring: 'do' matches nothing
    rs = sess.sql("select id from doc where fts_match(body, 'do')")
    assert rs.nrows == 0


def test_text_lob_columns_roundtrip():
    """TEXT/BLOB map onto dict-encoded varchar: unbounded values store
    once in the dictionary and round-trip through DML + fts_match."""
    from oceanbase_tpu.server.database import Database

    db = Database(n_nodes=1, n_ls=1)
    try:
        s = db.session()
        s.sql("create table notes (id int primary key, body text)")
        long = "x" * 10000 + " end"
        s.sql(f"insert into notes values (1, '{long}'), (2, 'short note')")
        rs = s.sql("select id from notes where fts_match(body, 'end')")
        assert [int(r[0]) for r in rs.rows()] == [1]
        assert s.sql(
            "select body from notes where id = 1").rows()[0][0] == long
    finally:
        db.close()


def test_composes_with_predicates_and_aggs(sess):
    rs = sess.sql(
        "select count(*) as n from doc "
        "where fts_match(body, 'fox') and id >= 1"
    )
    assert int(rs.columns["n"][0]) == 1
