"""Test environment: CPU with 8 virtual devices (default) or the real TPU.

Mirrors the reference's test pyramid decision (SURVEY.md §4): multi-"node"
behavior is exercised on one host. A virtual 8-device CPU platform stands
in for a TPU slice so sharding/collective paths compile and run in CI
without TPU hardware. Must run before any jax import.

`OB_TPU_TESTS=1` runs the suite on the REAL chip instead (VERDICT r1 weak
item 3: the target platform was only ever exercised by two queries).
Tests that require a multi-device mesh declare `@pytest.mark.multidevice`
and are skipped on a single chip.
"""

import os

ON_TPU = os.environ.get("OB_TPU_TESTS", "") == "1"

if not ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not ON_TPU:
    # A sitecustomize hook may have force-registered an accelerator backend
    # at interpreter startup, overriding JAX_PLATFORMS. jax.config overrides
    # a *registered* backend, but is a silent no-op once a backend is
    # *initialized* — check so tests fail loudly instead of running on a
    # 1-device accelerator mesh.
    jax.config.update("jax_platforms", "cpu")
    if not (jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8):
        # Not a bare assert: that would be compiled out under python -O and
        # silently run tests on a 1-device accelerator mesh.
        raise RuntimeError(
            f"test env needs 8 virtual CPU devices, got {jax.devices()}; a "
            "backend was initialized before conftest ran"
        )

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    if not ON_TPU:
        return
    n_dev = len(jax.devices())
    skip_multi = pytest.mark.skip(
        reason=f"needs a multi-device mesh; {n_dev} real device(s) present"
    )
    for item in items:
        if "multidevice" in item.keywords and n_dev < 4:
            item.add_marker(skip_multi)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "multidevice: needs >=4 devices (virtual CPU mesh or slice)"
    )
    config.addinivalue_line(
        "markers",
        "slow: long chaos/workload drives, excluded from tier-1 "
        "(opt in with tools/run_tier1.sh --chaos or -m slow)",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(42)
