"""Test environment: force CPU with 8 virtual devices.

Mirrors the reference's test pyramid decision (SURVEY.md §4): multi-"node"
behavior is exercised on one host. Here a virtual 8-device CPU platform
stands in for a TPU slice so sharding/collective paths compile and run in CI
without TPU hardware. Must run before any jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A sitecustomize hook may have force-registered an accelerator backend at
# interpreter startup, overriding JAX_PLATFORMS. jax.config overrides a
# *registered* backend, but is a silent no-op once a backend is
# *initialized* — assert so tests fail loudly instead of running on a
# 1-device accelerator mesh.
jax.config.update("jax_platforms", "cpu")
if not (jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8):
    # Not a bare assert: that would be compiled out under python -O and
    # silently run tests on a 1-device accelerator mesh.
    raise RuntimeError(
        f"test env needs 8 virtual CPU devices, got {jax.devices()}; a "
        "backend was initialized before conftest ran"
    )

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
