"""Test environment: force CPU with 8 virtual devices.

Mirrors the reference's test pyramid decision (SURVEY.md §4): multi-"node"
behavior is exercised on one host. Here a virtual 8-device CPU platform
stands in for a TPU slice so sharding/collective paths compile and run in CI
without TPU hardware. Must run before any jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
