"""TLS on the cluster bus and the MySQL front door (ussl-hook analog).

Certificates are generated per-test-session with the openssl CLI: one
cluster CA signing one shared cluster cert — the reference's trust shape
(certs identify the cluster, not hosts)."""

import socket
import ssl
import subprocess
import threading
import time

import pytest

from oceanbase_tpu.log.tcp_transport import TcpBus
from oceanbase_tpu.share.tls import client_context, server_context


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    ca_key, ca_crt = d / "ca.key", d / "ca.crt"
    key, csr, crt = d / "node.key", d / "node.csr", d / "node.crt"
    run = lambda *a: subprocess.run(a, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=oceanbase-tpu-test-ca")
    run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(key), "-out", str(csr),
        "-subj", "/CN=oceanbase-tpu-cluster")
    run("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
        "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
        "-days", "1")
    return {"ca": str(ca_crt), "crt": str(crt), "key": str(key)}


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _bus_pair(certs, token_a=b"tok", token_b=b"tok", b_tls=True):
    p1, p2 = _free_ports(2)
    tls_pair = lambda: (
        server_context(certs["crt"], certs["key"], cafile=certs["ca"]),
        client_context(certs["ca"], certs["crt"], certs["key"]),
    )
    a = TcpBus(p1, {2: ("127.0.0.1", p2)}, {1}, auth_token=token_a,
               tls=tls_pair())
    b = TcpBus(p2, {1: ("127.0.0.1", p1)}, {2}, auth_token=token_b,
               tls=tls_pair() if b_tls else None)
    a.start()
    b.start()
    return a, b


def test_bus_roundtrip_over_tls(certs):
    from oceanbase_tpu.share.deadlock import LockProbe

    a, b = _bus_pair(certs)
    got = []
    b.register(2, lambda src, msg: got.append((src, msg)))
    a.register(1, lambda src, msg: None)
    try:
        probe = LockProbe(7, 8, 9, 1, 42)
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            a.send(1, 2, probe)
            time.sleep(0.05)
        assert got and got[0] == (1, probe)
    finally:
        a.stop()
        b.stop()


def test_bus_rejects_non_tls_peer(certs):
    """A plaintext client against a TLS listener must be rejected, not
    interpreted as frames."""
    from oceanbase_tpu.share.deadlock import LockProbe

    a, b = _bus_pair(certs)
    got = []
    b.register(2, lambda src, msg: got.append(msg))
    try:
        # plaintext bus dialing the TLS listener: its frames are TLS
        # garbage to the server handshake
        p_plain = _free_ports(1)[0]
        plain = TcpBus(p_plain, {2: ("127.0.0.1", b.listen_port)}, {3},
                       auth_token=b"tok")
        plain.start()
        for _ in range(5):
            plain.send(3, 2, LockProbe(1, 2, 3, 1, 0))
            time.sleep(0.05)
        time.sleep(0.3)
        assert not got
        plain.stop()
    finally:
        a.stop()
        b.stop()


def test_bus_rejects_unverified_cert(certs, tmp_path):
    """mTLS: a client with a self-signed (non-cluster-CA) cert fails the
    server's verification."""
    run = lambda *a: subprocess.run(a, check=True, capture_output=True)
    rogue_key, rogue_crt = tmp_path / "r.key", tmp_path / "r.crt"
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(rogue_key), "-out", str(rogue_crt), "-days", "1",
        "-subj", "/CN=rogue")
    p1 = _free_ports(1)[0]
    srv = TcpBus(p1, {}, {1}, auth_token=b"tok", tls=(
        server_context(certs["crt"], certs["key"], cafile=certs["ca"]),
        client_context(certs["ca"], certs["crt"], certs["key"]),
    ))
    got = []
    srv.register(1, lambda src, msg: got.append(msg))
    srv.start()
    try:
        rogue_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        rogue_ctx.check_hostname = False
        rogue_ctx.verify_mode = ssl.CERT_NONE
        rogue_ctx.load_cert_chain(str(rogue_crt), str(rogue_key))
        raw = socket.create_connection(("127.0.0.1", p1), timeout=2)
        with pytest.raises(ssl.SSLError):
            s = rogue_ctx.wrap_socket(raw)
            # server aborts during/after handshake on cert verify
            s.sendall(b"x" * 64)
            for _ in range(10):
                s.sendall(b"x" * 64)
                time.sleep(0.05)
        assert not got
    finally:
        srv.stop()


def test_mysql_front_tls(certs):
    """Full MySQL login + query over protocol-negotiated TLS: greeting in
    plaintext, SSLRequest, handshake upgrade, login + COM_QUERY over the
    encrypted channel (what every stock client does with ssl-mode on)."""
    import struct

    from oceanbase_tpu.server.database import Database
    from oceanbase_tpu.server.mysql_front import MySqlFrontend

    from test_mysql_front import MiniMySqlClient

    class TlsClient(MiniMySqlClient):
        def __init__(self, port, user, password, cafile):
            self.sock = socket.create_connection(
                ("127.0.0.1", port), timeout=10)
            self.seq = 0
            greeting = self._read()
            nul = greeting.index(b"\x00", 1)
            p = nul + 1 + 4
            salt = greeting[p:p + 8]
            caps_lo = int.from_bytes(
                greeting[p + 8 + 1:p + 8 + 3], "little")
            assert caps_lo & 0x0800, "server did not advertise CLIENT_SSL"
            p += 8 + 1 + 2 + 1 + 2 + 2 + 1 + 10
            salt += greeting[p:greeting.index(b"\x00", p)]
            caps = 0x0200 | 0x8000 | 0x0800
            # SSLRequest: caps/maxpacket/charset only, then upgrade
            self._send(struct.pack("<IIB23x", caps, 1 << 24, 33))
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.load_verify_locations(cafile)
            ctx.check_hostname = False
            self.sock = ctx.wrap_socket(self.sock)
            from oceanbase_tpu.server.mysql_front import (
                native_password_scramble,
            )

            auth = native_password_scramble(password, salt[:20])
            self._send(
                struct.pack("<IIB23x", caps, 1 << 24, 33)
                + user.encode() + b"\x00"
                + bytes([len(auth)]) + auth
            )
            ok = self._read()
            assert ok[0] == 0x00, ok

    db = Database(n_nodes=1, n_ls=1)
    s = db.session()
    s.sql("create table t (a int primary key, b int)")
    s.sql("insert into t values (1, 10), (2, 20)")
    front = MySqlFrontend(
        db, users={"root": "secret"},
        ssl_context=server_context(certs["crt"], certs["key"]),
    ).start()
    try:
        c = TlsClient(front.port, "root", "secret", certs["ca"])
        names, rows = c.query("select sum(b) as s from t")
        assert names == ["s"] and rows == [("30",)]
        # and the socket really is TLS
        assert isinstance(c.sock, ssl.SSLSocket)
    finally:
        front.stop()
        db.close()
