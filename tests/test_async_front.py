"""Async MySQL front door (server/async_front.py).

The async server multiplexes every connection on one event loop and
runs statements on a bounded worker pool — but its WIRE surface must be
indistinguishable from the threaded MySqlFrontend: both feed the same
response builders, so COM_QUERY / COM_STMT_EXECUTE responses are
byte-identical frame-for-frame (the byte-identity test drives the same
command script at both servers over raw sockets and compares every
(seq, payload) pair). Also covered: COM_STMT_RESET on both servers,
abrupt-disconnect session teardown (workload digests reconcile, open
transactions roll back and release their locks), and a concurrent
wire workload riding the continuous-batching gate.
"""

import struct
import threading
import time

import pytest

from oceanbase_tpu.server.async_front import AsyncMySqlFrontend
from oceanbase_tpu.server.database import Database
from oceanbase_tpu.server.mysql_front import MySqlFrontend

from test_mysql_front import MiniMySqlClient

N_KEYS = 50


def _mkdb():
    db = Database(n_nodes=1, n_ls=1)
    s = db.session()
    s.sql("create table kv (id int primary key, k int, v int)")
    rows = ", ".join(f"({i + 1}, {i}, {i * 7 + 3})" for i in range(N_KEYS))
    s.sql(f"insert into kv values {rows}")
    for k in range(3):
        s.sql(f"select v from kv where k = {k}").rows()
    return db


@pytest.fixture(scope="module")
def db():
    d = _mkdb()
    yield d
    d.close()


@pytest.fixture(scope="module")
def afront(db):
    fe = AsyncMySqlFrontend(db).start()
    yield fe
    fe.stop()


@pytest.fixture(scope="module")
def tfront(db):
    fe = MySqlFrontend(db).start()
    yield fe
    fe.stop()


def _until(cond, timeout=10.0) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------------------- raw frame helpers


def _read_n(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        c = sock.recv(n - len(buf))
        if not c:
            raise ConnectionError("closed")
        buf += c
    return buf


def _read_frame(sock) -> tuple[int, bytes]:
    head = _read_n(sock, 4)
    return head[3], _read_n(sock, int.from_bytes(head[:3], "little"))


def _send_cmd(sock, payload: bytes) -> None:
    sock.sendall(len(payload).to_bytes(3, "little") + b"\x00" + payload)


def _read_resultset(sock) -> list[tuple[int, bytes]]:
    """Frames of one COM_QUERY / COM_STMT_EXECUTE response: a lone
    OK/ERR, or coldefs + rows closed by the second EOF."""
    frames = [_read_frame(sock)]
    if frames[0][1][0] in (0x00, 0xFF):
        return frames
    eofs = 0
    while eofs < 2:
        f = _read_frame(sock)
        frames.append(f)
        if f[1][0] == 0xFE and len(f[1]) < 9:
            eofs += 1
    return frames


def _read_prepare(sock, nparams: int) -> list[tuple[int, bytes]]:
    frames = [_read_frame(sock)]
    if frames[0][1][0] == 0xFF:
        return frames
    for _ in range(nparams + (1 if nparams else 0)):  # defs + EOF
        frames.append(_read_frame(sock))
    return frames


def _exec_packet(sid: int, params: tuple, send_types: bool = True) -> bytes:
    if not params:
        return (b"\x17" + sid.to_bytes(4, "little") + b"\x00"
                + (1).to_bytes(4, "little"))
    nb = (len(params) + 7) // 8
    bitmap = bytearray(nb)
    types = bytearray()
    values = bytearray()
    for i, v in enumerate(params):
        if v is None:
            bitmap[i // 8] |= 1 << (i % 8)
            types += bytes([8, 0])
        elif isinstance(v, int):
            types += bytes([8, 0])
            values += v.to_bytes(8, "little", signed=True)
        elif isinstance(v, float):
            types += bytes([5, 0])
            values += struct.pack("<d", v)
        else:
            s = str(v).encode()
            types += bytes([253, 0])
            values += bytes([len(s)]) + s
    return (
        b"\x17" + sid.to_bytes(4, "little") + b"\x00"
        + (1).to_bytes(4, "little") + bytes(bitmap)
        + ((b"\x01" + bytes(types)) if send_types else b"\x00")
        + bytes(values)
    )


# ---------------------------------------------------------- basic surface


def test_async_query_prepare_execute(afront):
    c = MiniMySqlClient(afront.port)
    assert b"oceanbase-tpu" in c.server_version
    assert c.ping()
    names, rows = c.query("select v from kv where k = 7")
    assert names == ["v"] and rows == [(str(7 * 7 + 3),)]
    with pytest.raises(RuntimeError, match="ERR"):
        c.query("select * from nonexistent_table")
    assert c.ping()  # connection survives an error
    sid, np_ = c.prepare("select v from kv where k = ? order by v")
    assert np_ == 1
    types, rows = c.execute(sid, (4,))
    assert types == [8] and rows == [(4 * 7 + 3,)]
    # driver-style re-execute without a type block
    _t, rows2 = c.execute(sid, (5,), send_types=False)
    assert rows2 == [(5 * 7 + 3,)]
    c.close()


def test_async_transaction_spans_statements(afront):
    c1 = MiniMySqlClient(afront.port)
    c2 = MiniMySqlClient(afront.port)
    c1.query("create table tx1 (id bigint primary key, v int)")
    c1.query("begin")
    c1.query("insert into tx1 values (1, 1)")
    _, rows = c2.query("select id from tx1")
    assert rows == []
    c1.query("commit")
    _, rows = c2.query("select id from tx1")
    assert rows == [("1",)]
    c1.close()
    c2.close()


def test_stmt_reset_both_servers(afront, tfront):
    for port in (afront.port, tfront.port):
        c = MiniMySqlClient(port)
        sid, _ = c.prepare("select v from kv where k = ?")
        _t, r1 = c.execute(sid, (2,))
        assert r1 == [(2 * 7 + 3,)]
        # COM_STMT_RESET: OK, forgets remembered types — the next
        # execute re-sends them (what compliant drivers do)
        c.seq = 0
        c._send(b"\x1a" + sid.to_bytes(4, "little"))
        assert c._read()[0] == 0x00
        _t, r2 = c.execute(sid, (3,), send_types=True)
        assert r2 == [(3 * 7 + 3,)]
        # unknown statement id -> ERR 1243
        c.seq = 0
        c._send(b"\x1a" + (9999).to_bytes(4, "little"))
        err = c._read()
        assert err[0] == 0xFF
        assert int.from_bytes(err[1:3], "little") == 1243
        c.close()


# ---------------------------------------------------------- byte identity


def _run_script(port) -> list[list[tuple[int, bytes]]]:
    """One fixed command script over a raw post-login socket; returns
    every response as (seq, payload) frames."""
    c = MiniMySqlClient(port)
    sock = c.sock
    out = []
    # text protocol: resultset, OK, ERR
    for q in (
        "select id, k, v from kv where k <= 5 order by k",
        "set ob_batch_max_wait_us = 1000",
        "select v from nonexistent_table",
    ):
        _send_cmd(sock, b"\x03" + q.encode())
        out.append(_read_resultset(sock))
    # binary protocol: prepare, execute, re-execute sans types, reset,
    # execute after reset
    _send_cmd(sock, b"\x16" + b"select v, s2 from kv2 where k >= ?")
    out.append(_read_prepare(sock, 1))
    sid = 1
    _send_cmd(sock, _exec_packet(sid, (3,)))
    out.append(_read_resultset(sock))
    _send_cmd(sock, _exec_packet(sid, (4,), send_types=False))
    out.append(_read_resultset(sock))
    _send_cmd(sock, b"\x1a" + sid.to_bytes(4, "little"))
    out.append([_read_frame(sock)])
    _send_cmd(sock, _exec_packet(sid, (2,)))
    out.append(_read_resultset(sock))
    # unsupported command surfaces the same ERR
    _send_cmd(sock, b"\x1f")
    out.append([_read_frame(sock)])
    c.close()
    return out


def test_async_byte_identical_to_threaded(db, afront, tfront):
    """The same script (COM_QUERY incl. doubles/quoted strings/errors,
    COM_STMT_PREPARE/EXECUTE/RESET) produces byte-identical response
    frames — sequence numbers included — from both servers."""
    s = db.session()
    s.sql("create table kv2 (id bigint primary key, k int, v double, "
          "s2 varchar)")
    s.sql("insert into kv2 values (1, 2, 2.5, 'two'), (2, 3, 3.75, 'three'), "
          "(3, 4, 4.25, 'it''s'), (4, 5, -1.0, 'five')")
    threaded = _run_script(tfront.port)
    asynced = _run_script(afront.port)
    assert len(threaded) == len(asynced)
    for i, (t, a) in enumerate(zip(threaded, asynced)):
        assert t == a, f"response {i} differs:\n threaded={t}\n async={a}"


# ------------------------------------------------------------- disconnect


def test_abrupt_disconnect_closes_session(db, afront):
    """Killing the socket (no COM_QUIT) must drop the engine session:
    the workload-repo accumulator flushes promptly and an open
    transaction rolls back, releasing its row locks."""
    c = MiniMySqlClient(afront.port)
    c.query("create table dx (id bigint primary key, v int)")
    n0 = sum(d["exec_count"] for d in db.stmt_summary.snapshot())
    for k in range(5):
        c.query(f"select v from kv where k = {k}")
    c.query("begin")
    assert c.query("insert into dx values (999, 0)") == 1
    c.sock.close()  # abrupt: no COM_QUIT

    # digest counts reconcile once the server notices the disconnect
    assert _until(lambda: sum(
        d["exec_count"] for d in db.stmt_summary.snapshot()) >= n0 + 5)

    # the uncommitted insert rolled back: its pk lock is free again and
    # the row is gone
    c2 = MiniMySqlClient(afront.port)

    def try_insert() -> bool:
        try:
            return c2.query("insert into dx values (999, 1)") == 1
        except RuntimeError:
            return False

    assert _until(try_insert)
    _, rows = c2.query("select v from dx where id = 999")
    assert rows == [("1",)]
    c2.close()


# ------------------------------------------------- concurrency + batching


def test_async_concurrent_wire_sessions_batch(db, afront):
    """12 concurrent wire connections through the async server: every
    statement answers correctly and eligible fast-path hits ride the
    dispatch gate (solo or batched — both counted)."""
    nthreads, nkeys = 12, 10
    errors: list = []
    outs: list = [None] * nthreads
    barrier = threading.Barrier(nthreads)
    c0 = db.metrics.counters_snapshot()

    def worker(i: int) -> None:
        try:
            c = MiniMySqlClient(afront.port)
            c.query("set ob_batch_max_size = 8")
            c.query("set ob_batch_max_wait_us = 1000")
            barrier.wait()
            got = []
            for j in range(nkeys):
                k = (i * 7 + j) % N_KEYS
                _n, rows = c.query(f"select v from kv where k = {k}")
                got.append((k, rows))
            outs[i] = got
            c.close()
        except Exception as e:  # pragma: no cover - surfaced by assert
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    for i in range(nthreads):
        assert outs[i] is not None, f"worker {i} produced nothing"
        for k, rows in outs[i]:
            assert rows == [(str(k * 7 + 3),)]
    c1 = db.metrics.counters_snapshot()
    gated = (
        c1.get("stmt batch solo", 0) - c0.get("stmt batch solo", 0)
        + c1.get("stmt batched statements", 0)
        - c0.get("stmt batched statements", 0)
    )
    assert gated > 0  # the wire workload reached the dispatch gate
    gate = db.batcher.gate
    assert _until(lambda: gate.busy == 0 and gate.queued_groups == 0)
