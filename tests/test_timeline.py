"""Serving timeline: bucket ring, QoS ledger, busy-fraction bounds.

Everything runs on an injected clock — no sleeps: busy seconds are real
perf_counter durations from real dispatches, wall seconds come from the
fake clock, so saturation tests drive hours of "time" in milliseconds.
"""

import threading

import pytest

from oceanbase_tpu.server import Database
from oceanbase_tpu.share.metrics import DEFAULT_BUCKETS, MetricsRegistry
from oceanbase_tpu.share.timeline import ServingTimeline, hist_quantile


def _tl(bucket_s=1.0, capacity=8):
    now = [0.0]
    tl = ServingTimeline(bucket_s=bucket_s, capacity=capacity,
                         clock=lambda: now[0])
    return tl, now


# ---- ring mechanics -------------------------------------------------------


def test_ring_wraps_and_memory_stays_bounded():
    tl, now = _tl(capacity=8)
    b0 = tl.stats()["bytes"]
    for i in range(50):
        now[0] = i + 0.5
        tl.record_exec(0.01, 0.0, 0)
        tl.record_stmt("sys", 0.02, False, 1)
    snap = tl.snapshot()
    assert len(snap) <= 8
    # the ring kept only the newest periods, oldest first
    assert [b["ts"] for b in snap] == [float(i) for i in range(42, 50)]
    st = tl.stats()
    assert st["buckets"] == 8
    assert st["records"] == 100
    # wraparound reuses buckets in place: footprint only grew by the
    # per-tenant ledgers, not with the 50 periods written
    assert st["bytes"] - b0 < 2048


def test_bucket_accounting_and_partial_wall():
    tl, now = _tl()
    now[0] = 10.2
    tl.record_stmt("sys", 0.05, True, 3)
    tl.record_admission("sys", 0.004, True)
    tl.record_exec(0.2, 0.1, 64)
    tl.record_batch(0.3, 5)
    tl.record_transfer(128)
    now[0] = 10.5  # still inside bucket 10
    (b,) = tl.snapshot()
    assert b["ts"] == 10.0
    assert b["wall_s"] == pytest.approx(0.5)  # partial bucket: elapsed
    assert b["stmts"] == 1 and b["errors"] == 1
    assert b["host_busy_s"] == pytest.approx(0.05)
    assert b["device_busy_s"] == pytest.approx(0.5)  # exec + batch
    assert b["device_busy_frac"] == pytest.approx(1.0)  # clamped at 1
    assert b["dispatches"] == 2 and b["batch_dispatches"] == 1
    assert b["batch_lanes"] == 5
    assert b["compile_events"] == 1
    assert b["compile_s"] == pytest.approx(0.1)
    assert b["transfer_events"] == 2
    assert b["transfer_bytes"] == 192
    assert b["max_in_flight"] == 3
    assert b["admission_wait_s"] == pytest.approx(0.004)
    assert sum(b["occ_hist"]) == 1 and sum(b["depth_hist"]) == 1
    assert b["wait_p99_s"] == hist_quantile(
        DEFAULT_BUCKETS, b["wait_hist"], 0.99)
    t = b["tenants"]["sys"]
    assert t["stmts"] == 1 and t["errors"] == 1
    assert t["wait_s"] == pytest.approx(0.004)
    # a full bucket later reports full wall and a lower busy fraction
    now[0] = 11.0
    tl.record_exec(0.001, 0.0, 0)
    now[0] = 12.4
    first = tl.snapshot()[0]
    assert first["wall_s"] == pytest.approx(1.0)


def test_qos_totals_survive_ring_wraparound():
    """The cumulative ledger is monotone: two reads diff exactly even
    after the bucket ring wrapped many times between them."""
    tl, now = _tl(capacity=4)
    tl.register_tenant("a", max_workers=4, queue_timeout_s=0.5)
    tl.register_tenant("b", max_workers=None, queue_timeout_s=0.0)
    q0 = tl.qos_totals()
    assert q0["a"]["max_workers"] == 4
    assert q0["b"]["max_workers"] == -1  # unbounded
    for i in range(40):  # 10x the ring capacity
        now[0] = float(i)
        tl.record_stmt("a", 0.01, False, 2)
        tl.record_admission("b", 0.002, i % 2 == 0)
    q1 = tl.qos_totals()
    assert q1["a"]["stmts"] - q0["a"]["stmts"] == 40
    assert q1["b"]["rejected"] - q0["b"]["rejected"] == 20
    assert q1["b"]["wait_s"] - q0["b"]["wait_s"] == pytest.approx(0.08)
    assert len(tl.snapshot()) <= 4


def test_disabled_timeline_records_nothing():
    tl, now = _tl()
    tl.enabled = False
    tl.record_stmt("sys", 1.0, False, 1)
    tl.record_exec(1.0, 1.0, 1)
    tl.record_batch(1.0, 4)
    tl.record_admission("sys", 1.0, False)
    tl.record_transfer(9)
    assert tl.snapshot() == []
    assert tl.records == 0


def test_reconfigure_bucket_width_and_capacity():
    tl, now = _tl(bucket_s=1.0, capacity=8)
    now[0] = 3.5
    tl.record_exec(0.1, 0.0, 0)
    tl.set_bucket_s(0.5)  # re-keys the ring: old periods dropped
    assert tl.snapshot() == []
    tl.record_exec(0.2, 0.0, 0)
    (b,) = tl.snapshot()
    assert b["ts"] == 3.5  # period 7 * 0.5s
    tl.set_capacity(16)
    assert tl.stats()["capacity"] == 16
    assert tl.snapshot() == []  # reallocated ring starts empty


def test_meter_publishes_sysstat_gauges():
    tl, now = _tl()
    now[0] = 0.25
    tl.record_exec(0.05, 0.0, 0)
    m = MetricsRegistry()
    tl.meter(m)
    g = m.gauges_snapshot()
    assert g["timeline buckets"] == 1
    assert g["timeline records"] == 1
    assert g["timeline bytes"] > 0
    assert g["timeline device busy pct"] == pytest.approx(20.0, rel=0.01)


# ---- end-to-end: virtual table busy-fraction bounds -----------------------


@pytest.fixture(scope="module")
def loaded_db():
    d = Database(n_nodes=1, n_ls=1)
    s = d.session()
    s.sql("create table tlv (k bigint primary key, v bigint not null)")
    s.sql("insert into tlv values " + ", ".join(
        f"({i}, {i * 3})" for i in range(1, 33)))
    # compile + cache every statement text the tests replay, so a cold
    # compile can never masquerade as device-busy time in a bucket
    for k in (5, 7, 9, 11, 13):
        s.sql(f"select v from tlv where k = {k}")
    return d


def test_vt_busy_fraction_trickle_vs_concurrent_load(loaded_db):
    """__all_virtual_server_timeline must separate a near-idle trickle
    from saturating load: one statement per 10 fake seconds yields a low
    device-busy fraction; 8 session threads hammering inside HALF of one
    frozen bucket yield a strictly higher one — and both stay <= 100%.
    Real dispatches supply the busy seconds; the fake clock supplies the
    wall, so no sleeps anywhere."""
    db = loaded_db
    now = [1000.0]
    old_clock = db.timeline._clock
    db.timeline._clock = lambda: now[0]
    try:
        s = db.session()
        trickle_periods = []
        for i in range(5):
            now[0] = 1010.0 + 10.0 * i  # one statement per 10 buckets
            trickle_periods.append(1010.0 + 10.0 * i)
            s.sql("select v from tlv where k = 7")
        now[0] = 1100.25  # trickle buckets are now complete (wall = 1s)

        rows = s.sql(
            "select bucket_ts, device_busy_pct from "
            "__all_virtual_server_timeline"
        ).rows()
        by_ts = {float(ts): float(pct) for ts, pct in rows}
        trickle = [by_ts[int(ts // 1.0)] for ts in trickle_periods
                   if int(ts // 1.0) in by_ts]
        assert trickle, by_ts
        assert all(0.0 <= p <= 100.0 for p in by_ts.values())

        # saturate: 8 threads, 12 statements each, all inside the first
        # half of one frozen bucket
        now[0] = 1200.5
        errs = []

        def hammer():
            try:
                sess = db.session()
                for _ in range(12):
                    sess.sql("select v from tlv where k = 9")
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=hammer) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        rows = s.sql(
            "select bucket_ts, device_busy_pct, stmts from "
            "__all_virtual_server_timeline where bucket_ts >= 1200"
        ).rows()
        (loaded,) = [(float(p), int(n)) for ts_, p, n in rows
                     if float(ts_) == 1200.0]
        loaded_pct, loaded_stmts = loaded
        assert loaded_stmts >= 96
        assert loaded_pct <= 100.0
        assert loaded_pct > max(trickle), (loaded_pct, trickle)
    finally:
        db.timeline._clock = old_clock


def test_vt_tenant_qos_live(loaded_db):
    s = loaded_db.session()
    s.sql("select v from tlv where k = 11")
    rows = s.sql(
        "select tenant, stmts, admitted from __all_virtual_tenant_qos"
    ).rows()
    by_tenant = {r[0]: r for r in rows}
    t = by_tenant[loaded_db.tenant_name]
    assert int(t[1]) > 0 and int(t[2]) > 0


def test_timeline_config_toggles(loaded_db):
    db = loaded_db
    db.config.set("enable_serving_timeline", "false")
    try:
        r0 = db.timeline.records
        db.session().sql("select v from tlv where k = 13")
        assert db.timeline.records == r0
    finally:
        db.config.set("enable_serving_timeline", "true")
    db.session().sql("select v from tlv where k = 13")
    assert db.timeline.records > r0
    db.config.set("serving_timeline_capacity", "16")
    assert db.timeline.stats()["capacity"] == 16
    db.config.set("serving_timeline_capacity", "120")
