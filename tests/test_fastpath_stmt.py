"""Statement fast path: text-keyed plan-cache tier + lazy device results.

Covers the serving-path contract end to end:
- literal re-binding across repeats (ints, floats, dates, strings,
  dtype widening, NULL-bearing statements, escaped strings);
- non-cacheable statements (DDL, SET, virtual tables, transactions, PX)
  bypass the tier;
- capacity eviction, flush() clearing BOTH tiers, DDL invalidation;
- the retry-policy regression: a flush_plan_cache retry (schema version
  mismatch) must never replay a stale text entry on the redrive;
- privileges re-checked on every fast hit (REVOKE bites a warm entry);
- lazy results: correct rows under LIMIT, correct full materialization.

NOTE tests/test_fastpath.py covers JOIN algorithm fast paths (unrelated).
"""

import numpy as np
import pytest

from oceanbase_tpu.engine.session import Session
from oceanbase_tpu.models.tpch import datagen
from oceanbase_tpu.share import retry as R
from oceanbase_tpu.sql.plan_cache import PlanCache, build_slot_map
from oceanbase_tpu.sql import parser as P


# ------------------------------------------------------------ unit: slot map


def test_fast_normalize_kind_markers():
    k1, p1, t1 = P.fast_normalize("select a from t where a = 5")
    k2, p2, t2 = P.fast_normalize("select a from t where a = '5'")
    assert k1 != k2  # a=5 and a='5' must never share a text entry
    assert "?n" in k1 and "?s" in k2
    assert p1 == ("5",) and p2 == ("5",)
    assert t1 == ("num",) and t2 == ("str",)
    # plain plan-cache key is recoverable by collapsing the markers
    assert k1.replace("?n", "?").replace("?s", "?") == \
        P.normalize_for_cache("select a from t where a = 5")[0]


def test_slot_map_unique_values_map_to_slots():
    # registration values: exactly the parameterized literals, distinct
    slot_map = build_slot_map(("5", "1.5"), ("num", "num"), [5, 1.5])
    assert slot_map[0][0] == "slot" and slot_map[1][0] == "slot"


def test_slot_map_ambiguous_values_bake():
    # the same value appears in two slots: exact-text match required
    slot_map = build_slot_map(("5", "5"), ("num", "num"), [5, 5])
    assert all(s[0] == "baked" for s in slot_map)


def test_slot_map_int_converter_refuses_float_token():
    from oceanbase_tpu.sql.plan_cache import _convert_token

    assert _convert_token("7", "int") == 7
    assert _convert_token("7.5", "int") is None  # widening: fast miss
    assert _convert_token("7.5", "float") == 7.5
    assert _convert_token("7", "float") is None  # would narrow the plan


# --------------------------------------------------------- engine level


@pytest.fixture(scope="module")
def esession():
    rng = np.random.default_rng(11)
    orders, lineitem = datagen.gen_orders_lineitem(0.01, rng, 1500, 2000, 100)
    return Session({"orders": orders, "lineitem": lineitem})


def _q6(d1, d2, lo, hi, qty):
    return (
        "select sum(l_extendedprice * l_discount) as revenue from lineitem "
        f"where l_shipdate >= date '{d1}' and l_shipdate < date '{d2}' "
        f"and l_discount between {lo} and {hi} and l_quantity < {qty}"
    )


def _q6_numpy(li, d1, d2, lo, hi, qty):
    ship, disc = li.data["l_shipdate"], li.data["l_discount"]
    qtyc, ep = li.data["l_quantity"], li.data["l_extendedprice"]
    m = (
        (ship >= int(np.datetime64(d1, "D").astype(np.int64)))
        & (ship < int(np.datetime64(d2, "D").astype(np.int64)))
        & (disc >= round(lo * 100)) & (disc <= round(hi * 100))
        & (qtyc < qty * 100)
    )
    return float(np.sum(ep[m].astype(np.int64) * disc[m].astype(np.int64))) / 1e4


def test_fast_hit_rebinds_dates_floats_ints(esession):
    li = esession.catalog["lineitem"]
    r1 = esession.sql(_q6("1994-01-01", "1995-01-01", 0.05, 0.07, 24))
    assert not r1.fast_path_hit
    h0 = esession.plan_cache.stats.fast_hits
    # different dates, different float bounds, different int threshold
    r2 = esession.sql(_q6("1995-01-01", "1996-01-01", 0.02, 0.09, 30))
    assert esession.plan_cache.stats.fast_hits == h0 + 1
    assert r2.fast_path_hit
    got = float(r2.rows()[0][0])
    want = _q6_numpy(li, "1995-01-01", "1996-01-01", 0.02, 0.09, 30)
    assert got == pytest.approx(want, rel=1e-9)


def test_fast_widening_falls_back_then_reregisters(esession):
    q = "select count(*) from lineitem where l_quantity < {}"
    esession.sql(q.format(20))
    r_int = esession.sql(q.format(25))
    assert r_int.fast_path_hit
    # widening: '25.5' refuses the int converter -> honest fast miss,
    # slow path plans the float variant and re-registers it
    m0 = esession.plan_cache.stats.fast_misses
    r_f = esession.sql(q.format(25.5))
    assert not r_f.fast_path_hit
    assert esession.plan_cache.stats.fast_misses == m0 + 1
    li = esession.catalog["lineitem"]
    assert r_f.rows()[0][0] == int((li.data["l_quantity"] < 2550).sum())
    r_f2 = esession.sql(q.format(30.5))
    assert r_f2.fast_path_hit
    assert r_f2.rows()[0][0] == int((li.data["l_quantity"] < 3050).sum())


def test_lazy_rows_limit_and_full(esession):
    q = "select l_orderkey, l_quantity from lineitem where l_discount >= 0.05"
    esession.sql(q)
    rs = esession.sql(q)
    assert rs.fast_path_hit
    li = esession.catalog["lineitem"]
    mask = li.data["l_discount"] >= 5  # stored scaled x100
    want_n = int(mask.sum())
    assert rs.nrows == want_n
    head = rs.rows(limit=3)
    assert len(head) == min(3, want_n)
    full = rs.rows()
    assert len(full) == want_n
    assert full[:3] == head
    want_keys = li.data["l_orderkey"][mask]
    assert [r[0] for r in full] == list(want_keys)


# --------------------------------------------------------- server level


@pytest.fixture()
def sdb():
    from oceanbase_tpu.server.database import Database

    db = Database(n_nodes=1, n_ls=1)
    s = db.session()
    s.sql("create table kv (id int primary key, k int, v int, "
          "name varchar(20))")
    s.sql("insert into kv values (1, 10, 100, 'aa'), (2, 20, 200, 'bb'), "
          "(3, 30, 300, 'it''s'), (4, 40, 400, 'dd')")
    return db, s


def test_server_fast_hit_and_audit(sdb):
    db, s = sdb
    q = "select v from kv where k = {}"
    assert s.sql(q.format(10)).rows() == [(100,)]
    r = s.sql(q.format(30))
    assert r.fast_path_hit and r.rows() == [(300,)]
    rec = [a for a in db.audit.records() if a.stmt_type == "Select"][-1]
    assert rec.is_fast_path
    assert rec.plan_cache_hit
    # breakdown recorded (dispatch always happens; parse/plan did not)
    assert rec.dispatch_us >= 0 and rec.compile_s == 0.0


def test_server_string_escape_and_null_literals(sdb):
    db, s = sdb
    q = "select id from kv where name = '{}'"
    assert s.sql(q.format("aa")).rows() == [(1,)]
    # string literals are BAKED (dictionary lookups trace-time baked):
    # a different string is an honest fast miss that re-registers...
    r = s.sql(q.format("it''s"))
    assert not r.fast_path_hit
    assert r.rows() == [(3,)]  # escaped quote parses correctly
    # ...and an exact repeat (same escapes) is a fast hit
    r2 = s.sql(q.format("it''s"))
    assert r2.fast_path_hit and r2.rows() == [(3,)]
    # NULL keyword statements ride the tier (null is text, not a param)
    qn = "select count(*) from kv where name is not null and k >= {}"
    assert s.sql(qn.format(0)).rows() == [(4,)]
    rn = s.sql(qn.format(35))
    assert rn.fast_path_hit and rn.rows() == [(1,)]


def test_server_ddl_set_and_vt_bypass(sdb):
    db, s = sdb
    st = db.plan_cache.stats
    h0 = st.fast_hits
    s.sql("set ob_px_dop = 0")
    s.sql("create table other (a int primary key)")
    s.sql("drop table other")
    assert st.fast_hits == h0  # none of those touched the tier
    # virtual-table selects are never registered: two runs, zero hits
    s.sql("select name, value from __all_virtual_sysstat where value > 0")
    s.sql("select name, value from __all_virtual_sysstat where value > 1")
    assert st.fast_hits == h0


def test_server_tx_bypasses_fast_path(sdb):
    db, s = sdb
    q = "select v from kv where k = {}"
    s.sql(q.format(10))
    assert s.sql(q.format(10)).fast_path_hit
    s.sql("begin")
    try:
        r = s.sql(q.format(10))
        assert not r.fast_path_hit  # in-tx reads keep the snapshot path
        assert r.rows() == [(100,)]
    finally:
        s.sql("rollback")


def test_flush_clears_both_tiers(sdb):
    db, s = sdb
    q = "select v from kv where k = {}"
    s.sql(q.format(10))
    assert s.sql(q.format(20)).fast_path_hit
    inv0 = db.plan_cache.stats.fast_invalidations
    db.plan_cache.flush()
    assert len(db.plan_cache._fast) == 0
    assert db.plan_cache.stats.fast_invalidations > inv0
    r = s.sql(q.format(30))  # miss, re-register, correct
    assert not r.fast_path_hit and r.rows() == [(300,)]
    assert s.sql(q.format(40)).fast_path_hit


def test_fast_capacity_eviction(sdb):
    db, s = sdb
    cap0 = db.plan_cache.capacity
    db.plan_cache.capacity = 2
    try:
        qs = ["select v from kv where k = 10 and id < {}",
              "select k from kv where v = 100 and id < {}",
              "select id from kv where k > 0 and id < {}"]
        ev0 = db.plan_cache.stats.fast_evictions
        for q in qs:
            s.sql(q.format(99))
        assert len(db.plan_cache._fast) <= 2
        assert db.plan_cache.stats.fast_evictions > ev0
        # evicted statement is a miss, still correct, re-registers
        r = s.sql(qs[0].format(98))
        assert r.rows() == [(100,)]
    finally:
        db.plan_cache.capacity = cap0


def test_ddl_invalidates_stale_text_entry(sdb):
    db, s = sdb
    q = "select sum(v) from kv where k < {}"
    s.sql(q.format(100))
    assert s.sql(q.format(100)).fast_path_hit
    # drop + recreate with DIFFERENT data: a stale replay would return
    # the old sums
    s.sql("drop table kv")
    s.sql("create table kv (id int primary key, k int, v int, "
          "name varchar(20))")
    s.sql("insert into kv values (1, 10, 7, 'x')")
    r = s.sql(q.format(100))
    assert r.rows() == [(7,)]


def test_retry_flush_never_replays_stale_text_entry(sdb):
    """The server/database.py retry-policy hole: a flush_plan_cache
    policy (OB_SCHEMA_EAGAIN) must flush the TEXT tier too — the redrive
    must re-resolve through the full path, not replay the text entry
    compiled against the dead schema."""
    db, s = sdb
    q = "select v from kv where k = {}"
    s.sql(q.format(10))
    assert s.sql(q.format(20)).fast_path_hit

    orig = db.engine.fast_execute
    fired = {"n": 0}

    def boom(hit, **kw):
        fired["n"] += 1
        raise R.SchemaVersionMismatch("injected: schema moved")

    db.engine.fast_execute = boom
    try:
        h0 = db.plan_cache.stats.fast_hits
        r = s.sql(q.format(30))  # fast hit raises -> retry flushes -> slow
        assert r.rows() == [(300,)]
        assert fired["n"] == 1  # the redrive did NOT re-enter the fast path
        assert db.plan_cache.stats.fast_hits == h0 + 1  # only the poisoned hit
    finally:
        db.engine.fast_execute = orig
    rec = [a for a in db.audit.records() if a.stmt_type == "Select"][-1]
    assert rec.retry_cnt == 1
    assert not rec.is_fast_path  # the statement that SUCCEEDED was slow-path
    # and the tier warms again afterwards
    s.sql(q.format(10))
    assert s.sql(q.format(40)).fast_path_hit


def test_privileges_bite_on_warm_fast_hits(sdb):
    db, s = sdb
    s.sql("create user bob")
    s.sql("grant select on kv to bob")
    sb = db.session(user="bob")
    q = "select v from kv where k = {}"
    sb.sql(q.format(10))
    assert sb.sql(q.format(20)).fast_path_hit  # warm under bob's grant
    s.sql("revoke select on kv from bob")
    from oceanbase_tpu.server.database import SqlError

    with pytest.raises(SqlError):
        sb.sql(q.format(30))  # warm text entry must NOT bypass the revoke


def test_sequence_draws_never_served_from_fast_tier(sdb):
    # nextval is side-effecting: _bind_sequences rewrites it into a fresh
    # literal pre-resolution; a text-keyed replay would freeze the value
    db, s = sdb
    s.sql("create sequence sq_fp")
    q = "select nextval('sq_fp') as v"
    vals = [int(s.sql(q).rows()[0][0]) for _ in range(4)]
    assert vals == [1, 2, 3, 4]
    assert db.plan_cache.fast_peek(
        P.fast_normalize(q)[0]) is None  # never registered


def test_sysstat_exposes_fast_counters(sdb):
    db, s = sdb
    q = "select v from kv where k = {}"
    s.sql(q.format(10))
    s.sql(q.format(20))
    rows = dict(s.sql(
        "select name, value from __all_virtual_sysstat "
        "where name like 'plan cache fast%'").rows())
    assert rows.get("plan cache fast hit", 0) >= 1
    assert rows.get("plan cache fast miss", 0) >= 1
