"""Closed-loop layout advisor: evidence -> ranked costed actions ->
background apply.

Covers the three control surfaces (ALTER SYSTEM RUN LAYOUT ADVISOR,
ob_layout_advisor_mode, __all_virtual_layout_advisor), the dry_run
no-mutation guarantee, hysteresis (stable action sets across snapshots,
idle-drop + no immediate re-create), the budget knob, DML invalidation
accounting + background rebuild re-queue, residency-priority-aware
eviction, and the tools/awr_report.py build_advisor() output contract
(satellite: the producer/consumer shape is pinned here, not prose).
"""

import os
import sys

import numpy as np
import pytest

from oceanbase_tpu.server.database import Database

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_db():
    d = Database(n_nodes=1, n_ls=1)
    s = d.session()
    s.sql("create table adv (id int primary key, d int, a int, b int)")
    s.sql("insert into adv values " + ", ".join(
        f"({i}, {i % 100}, {i * 2}, {i % 7})" for i in range(400)))
    return d


def _drive(db, lo=0, n=5):
    s = db.session()
    for k in range(lo, lo + n):
        s.sql(f"select sum(a) from adv where d >= {k} and d < {k + 3}").rows()


@pytest.fixture()
def db():
    d = _mk_db()
    yield d
    d.close()


# ---- control path ---------------------------------------------------------


def test_dry_run_proposes_and_mutates_nothing(db):
    _drive(db)
    s = db.session()
    rs = s.sql("alter system run layout advisor")
    acts = dict(zip(rs.columns["action"], rs.columns["status"]))
    assert acts.get("create_projection") == "dry_run"
    # nothing materialized, nothing queued, no priorities set
    assert getattr(db.catalog["adv"], "sorted_projections", {}) == {}
    assert db.dag_scheduler.pending == 0
    assert db.residency_priority == {}
    assert db.layout_advisor.created == {}


def test_run_requires_super(db):
    from oceanbase_tpu.server.database import SqlError

    with pytest.raises(SqlError) as ei:
        db.session(user="alice").sql("alter system run layout advisor")
    assert ei.value.code == 1227


def test_virtual_table_mirrors_last_pass(db):
    _drive(db)
    s = db.session()
    s.sql("alter system run layout advisor")
    rs = s.sql(
        "select action, table_name, column_name, status "
        "from __all_virtual_layout_advisor")
    rows = set(rs.rows())
    assert ("create_projection", "adv", "d", "dry_run") in rows
    assert any(a == "set_residency" and t == "adv"
               for a, t, _c, _st in rows)


def test_mode_param_validates_choices(db):
    from oceanbase_tpu.server.database import SqlError

    with pytest.raises(SqlError):
        db.session().sql("alter system set ob_layout_advisor_mode = bogus")


# ---- auto apply -----------------------------------------------------------


def test_auto_builds_projection_in_background_with_identical_results(db):
    s = db.session()
    _drive(db)
    q = "select sum(a) from adv where d >= 10 and d < 13"
    before = s.sql(q).rows()
    s.sql("alter system set ob_layout_advisor_mode = auto")
    rs = s.sql("alter system run layout advisor")
    st = dict(zip(rs.columns["action"], rs.columns["status"]))
    assert st["create_projection"] == "queued"
    assert db.dag_scheduler.pending == 1  # background, not statement path
    db.dag_scheduler.run_until_idle()
    assert getattr(db.catalog["adv"], "sorted_projections", {}) == {
        "d": "adv#sp:d"}
    # the rebuild dag surfaced as a long op
    ops = db.session().sql(
        "select op_name, status from __all_virtual_long_ops").rows()
    assert ("layout rebuild", "DONE") in ops
    # routed AND bit-identical
    assert s.sql(q).rows() == before
    hits = [r["proj_hits"] for r in db.access.snapshot()
            if r["table"] == "adv"]
    assert hits and hits[0] >= 1
    # residency priority applied for the hot table
    assert db.residency_priority.get("adv", 0) > 0


def test_dml_invalidation_counts_and_requeues_rebuild(db):
    s = db.session()
    _drive(db)
    s.sql("alter system set ob_layout_advisor_mode = auto")
    s.sql("alter system run layout advisor")
    db.dag_scheduler.run_until_idle()
    q = "select sum(a) from adv where d >= 1 and d < 2"
    c0 = db.metrics.counters_snapshot().get(
        "sorted projection invalidations", 0)
    s.sql("insert into adv values (9000, 1, 11, 0)")
    expect = s.sql(q).rows()  # DML visible even while layout is rebuilt
    assert db.metrics.counters_snapshot()[
        "sorted projection invalidations"] == c0 + 1
    assert db.dag_scheduler.pending == 1  # re-queued, not silently lost
    db.dag_scheduler.run_until_idle()
    assert getattr(db.catalog["adv"], "sorted_projections", {}) == {
        "d": "adv#sp:d"}
    assert s.sql(q).rows() == expect


# ---- hysteresis -----------------------------------------------------------


def test_actions_stable_across_consecutive_snapshots(db):
    s = db.session()
    s.sql("alter system set ob_layout_advisor_mode = dry_run")
    _drive(db)
    s.sql("snapshot workload")
    _drive(db)
    s.sql("snapshot workload")  # first on_snapshot-triggered pass
    set1 = {(r.action, r.table, r.column) for r in db.layout_advisor.last}
    _drive(db)
    s.sql("snapshot workload")  # same workload again
    set2 = {(r.action, r.table, r.column) for r in db.layout_advisor.last}
    assert set1 == set2
    assert ("create_projection", "adv", "d") in set1


def test_idle_projection_dropped_then_not_flapped_back(db):
    s = db.session()
    s.sql("alter system set ob_layout_advisor_mode = auto")
    _drive(db)
    s.sql("alter system run layout advisor")
    db.dag_scheduler.run_until_idle()
    assert ("adv", "d") in db.layout_advisor.created
    s.sql("snapshot workload")
    # workload shifts: the base table stays hot but never range-filters
    # on d, so the projection sits idle for DROP_AFTER_WINDOWS windows
    from oceanbase_tpu.server.layout_advisor import DROP_AFTER_WINDOWS

    for _ in range(DROP_AFTER_WINDOWS):
        for _k in range(4):
            s.sql("select sum(b) from adv").rows()
        s.sql("snapshot workload")
    db.dag_scheduler.run_until_idle()
    assert getattr(db.catalog["adv"], "sorted_projections", {}) == {}
    assert "adv#sp:d" not in db.catalog
    assert ("adv", "d") not in db.layout_advisor.created
    # the cumulative filter evidence that justified the build is still
    # in the counters: another pass must NOT immediately re-create
    recs = db.layout_advisor.run()
    assert not any(r.action == "create_projection" and r.table == "adv"
                   and r.status in ("proposed", "queued") for r in recs)
    # ...until NEW filtered scans arrive
    _drive(db, lo=20, n=5)
    recs = db.layout_advisor.run()
    assert any(r.action == "create_projection" and r.table == "adv"
               for r in recs)


def test_budget_narrows_then_rejects(db):
    s = db.session()
    _drive(db)
    s.sql("alter system set layout_advisor_max_bytes = 1")
    recs = db.layout_advisor.run()
    creates = [r for r in recs if r.action == "create_projection"]
    assert creates and creates[0].status == "rejected:budget"
    assert creates[0].detail.startswith("cover=")
    s.sql("alter system set layout_advisor_max_bytes = 64M")
    recs = db.layout_advisor.run()
    creates = [r for r in recs if r.action == "create_projection"]
    assert creates and creates[0].status == "dry_run"
    assert creates[0].cost_bytes > 0


# ---- encodings + residency ------------------------------------------------


def test_encoding_recommendation_from_cost_model():
    d = Database(n_nodes=1, n_ls=1)
    try:
        s = d.session()
        s.sql("create table enc_t (id int primary key, r bigint, x bigint)")
        # r has 4 long runs (RLE-friendly, > 4KB savings at 2000 rows)
        s.sql("insert into enc_t values " + ", ".join(
            f"({i}, {i // 500}, {i})" for i in range(2000)))
        for k in range(3):
            s.sql(f"select sum(x) from enc_t where r >= {k}").rows()
        recs = d.layout_advisor.run()
        encs = {(r.table, r.column): r.detail for r in recs
                if r.action == "set_encoding"}
        assert encs.get(("enc_t", "r")) == "rle"
        # auto mode records the hint
        s.sql("alter system set ob_layout_advisor_mode = auto")
        d.layout_advisor.run()
        assert d.layout_advisor.encoding_hints[("enc_t", "r")] == "rle"
    finally:
        d.close()


def test_kvcache_eviction_respects_priority():
    from oceanbase_tpu.share.cache import KVCache

    c = KVCache(capacity_bytes=3 * 800)
    c.priority_of = lambda key: 5.0 if key[0] == "hot" else 0.0
    c.put(("hot", 0), np.zeros(100))  # 800B, LRU-most
    c.put(("cold", 0), np.zeros(100))
    c.put(("cold", 1), np.zeros(100))
    c.put(("cold", 2), np.zeros(100))  # over budget: one must go
    assert c.get(("hot", 0)) is not None  # survived despite being LRU
    assert c.get(("cold", 0)) is None  # coldest zero-priority evicted
    assert c.evictions == 1


def test_enforce_memory_evicts_lowest_priority_first():
    d = Database(n_nodes=1, n_ls=1)
    try:
        s = d.session()
        for name in ("res_a", "res_b"):
            s.sql(f"create table {name} (id int primary key, v bigint)")
            s.sql(f"insert into {name} values " + ", ".join(
                f"({i}, {i})" for i in range(200)))
            s.sql(f"select sum(v) from {name}").rows()
        d.residency_priority["res_a"] = 9.0
        d.residency_priority["res_b"] = 1.0
        d.unit.memory_limit = d._resident_bytes() - 1
        d._enforce_memory(keep="res_a")
        # res_b (lower priority) lost its snapshot first
        assert d.tables["res_b"].cached_data_version == -1
        assert d.tables["res_a"].cached_data_version != -1
    finally:
        d.unit.memory_limit = None
        d.close()


# ---- producer/consumer contract (tools/awr_report.py) ---------------------


def test_build_advisor_output_contract():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from awr_report import build_advisor
    finally:
        sys.path.pop(0)

    digests = [{
        "digest": "select v from t where k = ?n", "stmt_type": "Select",
        "exec_count": 20, "total_time_s": 0.4, "avg_time_s": 0.02,
        "batched_count": 2, "fast_path_count": 18,
    }]
    tables = [{
        "table": "t", "scans": 12, "rows_read": 24000,
        "das_lookups": 0, "das_rows": 0, "proj_hits": 0, "proj_misses": 3,
        "columns": [
            {"column": "k", "filter_count": 12, "join_count": 0,
             "group_count": 0, "sort_count": 0},
        ],
    }]
    resid = [{"table": "t", "bytes": 4096}]
    out = build_advisor(digests, tables, resid)
    assert set(out) == {"sorted_projections", "residency_priorities",
                        "batching_candidates"}
    for key in out:
        assert isinstance(out[key], list)
    sp = out["sorted_projections"][0]
    assert set(sp) >= {"table", "column", "score", "reason"}
    assert (sp["table"], sp["column"]) == ("t", "k")
    rp = out["residency_priorities"][0]
    assert set(rp) >= {"table", "score", "scans", "device_bytes"}
    assert rp["table"] == "t"
    bc = out["batching_candidates"][0]
    assert set(bc) >= {"digest", "executions", "batched_ratio", "fast_ratio"}
    assert bc["batched_ratio"] == 0.1
