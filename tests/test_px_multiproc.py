"""Multi-process PX: the same shard_map programs over a GLOBAL mesh
spanning two OS processes (jax.distributed + gloo CPU collectives).

The DCN half of SURVEY §2.7: the reference runs PX across observers via
SQC RPC dispatch + DTL channels (sql/engine/px/ob_px_rpc_processor.h:28,
sql/dtl/ob_dtl_rpc_channel.h:44); here two processes each own 4 virtual
devices of one 8-device mesh, XLA routes the exchange collectives across
the process boundary, and results must match the single-process engine
bit for bit."""

import multiprocessing as mp
import os
import socket

import pytest

pytestmark = pytest.mark.multidevice

QIDS = (1, 3, 6)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _worker(pid: int, nprocs: int, port: int, q):
    try:
        # must run BEFORE any oceanbase_tpu import: package imports build
        # jnp constants, which initialise (and lock) the XLA backend
        import jax

        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=nprocs, process_id=pid,
        )

        assert len(jax.devices()) == 8, jax.devices()
        assert len(jax.local_devices()) == 4

        from oceanbase_tpu.core.column import batch_rows_normalized
        from oceanbase_tpu.models.tpch import datagen
        from oceanbase_tpu.models.tpch.sql_suite import QUERIES, UNIQUE_KEYS
        from oceanbase_tpu.parallel.mesh import make_mesh
        from oceanbase_tpu.parallel.px import PxExecutor
        from oceanbase_tpu.sql.parser import parse
        from oceanbase_tpu.sql.planner import Planner

        tables = datagen.generate(sf=0.01)  # deterministic: same everywhere
        mesh = make_mesh(8)
        planner = Planner(tables)
        px = PxExecutor(tables, mesh, unique_keys=UNIQUE_KEYS)
        out = {}
        for qid in QIDS:
            planned = planner.plan(parse(QUERIES[qid]))
            b = px.execute(planned.plan)
            out[qid] = batch_rows_normalized(b, planned.output_names)
        q.put(("ok", pid, out))
    except Exception as e:  # pragma: no cover - surfaced by the parent
        import traceback

        q.put(("err", pid, f"{e}\n{traceback.format_exc()}"))


def test_px_two_process_global_mesh():
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = _free_port()
    # children must see this env at INTERPRETER start (sitecustomize's
    # axon registration and jax platform selection both run before any
    # user code), so mutate the parent env around the spawn
    saved = {
        k: os.environ.get(k)
        for k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS")
    }
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        ctx.Process(target=_worker, args=(i, 2, port, q), daemon=True)
        for i in range(2)
    ]
    try:
        for p in procs:
            p.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    results = {}
    try:
        for _ in range(2):
            kind, pid, payload = q.get(timeout=600)
            assert kind == "ok", f"process {pid} failed:\n{payload}"
            results[pid] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()

    # both processes executed the same SPMD program: identical results
    assert results[0] == results[1]

    # and they match the single-process engine (this test process)
    from oceanbase_tpu.core.column import batch_rows_normalized
    from oceanbase_tpu.engine.executor import Executor
    from oceanbase_tpu.models.tpch import datagen
    from oceanbase_tpu.models.tpch.sql_suite import QUERIES, UNIQUE_KEYS
    from oceanbase_tpu.sql.parser import parse
    from oceanbase_tpu.sql.planner import Planner

    tables = datagen.generate(sf=0.01)
    planner = Planner(tables)
    single = Executor(tables, unique_keys=UNIQUE_KEYS)
    for qid in QIDS:
        planned = planner.plan(parse(QUERIES[qid]))
        b = single.execute(planned.plan)
        srows = batch_rows_normalized(b, planned.output_names)
        assert results[0][qid] == srows, f"q{qid} distributed mismatch"
        assert len(srows) > 0
