"""TPC-DS star-join suite vs a sqlite oracle (single-chip + PX).

BASELINE config 5's shape: selective dimension filters, star joins into a
fact table, wide GROUP BY, ORDER BY ... LIMIT. The generator is original
numpy (models/tpcds/datagen.py); query texts are the public TPC-DS spec
queries."""

import sqlite3

import numpy as np
import pytest

from oceanbase_tpu.engine import Session
from oceanbase_tpu.models.tpcds import QUERIES, UNIQUE_KEYS, datagen


@pytest.fixture(scope="module")
def db():
    tables = datagen.generate(sf=0.005)
    sess = Session(tables, unique_keys=UNIQUE_KEYS)
    conn = sqlite3.connect(":memory:")
    for name, t in tables.items():
        cols = t.schema.names()
        decoded = {}
        for c in cols:
            dt = t.schema[c]
            if dt.kind.value == "varchar":
                decoded[c] = t.dicts[c].decode(t.data[c])
            elif dt.is_decimal:
                decoded[c] = (t.data[c] / dt.decimal_factor).tolist()
            elif dt.kind.value == "date":
                base = np.datetime64("1970-01-01", "D")
                decoded[c] = [str(base + int(v)) for v in t.data[c]]
            else:
                decoded[c] = t.data[c].tolist()
        conn.execute(f"create table {name} ({', '.join(cols)})")
        rows = list(zip(*[decoded[c] for c in cols]))
        ph = ",".join("?" * len(cols))
        conn.executemany(f"insert into {name} values ({ph})", rows)
    conn.commit()
    return tables, sess, conn


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_star_join_vs_sqlite(db, qid):
    tables, sess, conn = db
    rs = sess.sql(QUERIES[qid])
    want = conn.execute(QUERIES[qid]).fetchall()
    got = [
        tuple(rs.columns[n][i] for n in rs.names)
        for i in range(rs.nrows)
    ]
    assert len(got) == len(want), (len(got), len(want))
    for g, w in zip(got, want):
        for gv, wv in zip(g, w):
            if isinstance(wv, float):
                assert float(gv) == pytest.approx(wv, rel=1e-6, abs=1e-2)
            elif isinstance(wv, str):
                assert str(gv) == wv
            else:
                assert int(gv) == int(wv)


@pytest.mark.multidevice
def test_star_join_px(db):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs a multi-device mesh")
    from oceanbase_tpu.core.column import batch_rows_normalized
    from oceanbase_tpu.engine.executor import Executor
    from oceanbase_tpu.parallel.mesh import make_mesh
    from oceanbase_tpu.parallel.px import PxExecutor
    from oceanbase_tpu.sql.parser import parse
    from oceanbase_tpu.sql.planner import Planner

    tables, _sess, _conn = db
    planner = Planner(tables)
    pq = planner.plan(parse(QUERIES[3]))
    single = Executor(tables, unique_keys=UNIQUE_KEYS).execute(pq.plan)
    px = PxExecutor(
        tables, make_mesh(8), unique_keys=UNIQUE_KEYS
    ).execute(pq.plan)
    srows = batch_rows_normalized(single, pq.output_names)
    prows = batch_rows_normalized(px, pq.output_names)
    assert srows == prows
