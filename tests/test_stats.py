"""Optimizer statistics: collection, selectivity, estimation integration.

Reference surface: src/share/stat (dbms_stats NDV/min-max/histograms) and
ob_opt_selectivity — here collected from catalog snapshot Tables and fed to
Planner._scan_rows / Executor._est_rows / hash-table capacity seeding.
"""

import numpy as np
import pytest

from oceanbase_tpu.core.dtypes import DataType, Field, Schema
from oceanbase_tpu.core.table import Table
from oceanbase_tpu.expr import ir as E
from oceanbase_tpu.share.stats import (
    StatsManager,
    collect_table_stats,
)


@pytest.fixture
def t():
    n = 10_000
    rng = np.random.default_rng(7)
    schema = Schema((
        Field("k", DataType.int64()),
        Field("grp", DataType.int32()),
        Field("price", DataType.decimal(12, 2)),
        Field("day", DataType.date()),
        Field("name", DataType.varchar(16)),
    ))
    names = rng.choice(["ann", "bob", "carol", "dave", "emma"], size=n)
    return Table.from_pydict("t", schema, {
        "k": np.arange(n, dtype=np.int64),
        "grp": rng.integers(0, 50, size=n).astype(np.int32),
        "price": rng.integers(0, 100_000, size=n).astype(np.int64),
        "day": rng.integers(18000, 19000, size=n).astype(np.int32),
        "name": names,
    })


def test_collect_basic_shapes(t):
    ts = collect_table_stats(t)
    assert ts.nrows == 10_000
    k = ts.cols["k"]
    assert k.vmin == 0 and k.vmax == 9999
    assert 9_000 <= k.ndv <= 10_000  # unique column
    g = ts.cols["grp"]
    assert 45 <= g.ndv <= 55  # 50 distinct values
    nm = ts.cols["name"]
    assert 4 <= nm.ndv <= 6  # 5 strings, stats on dict codes


def test_range_selectivity_tracks_truth(t):
    ts = collect_table_stats(t)
    # k < 2500 -> exactly 25%
    sel = ts.selectivity(
        E.Compare("<", E.col("a.k"), E.lit(2500, DataType.int64())), t
    )
    assert 0.2 <= sel <= 0.3
    # conjunction: k < 5000 and grp = 7 -> 0.5 * 1/50 = 1%
    pred = E.and_(
        E.Compare("<", E.col("a.k"), E.lit(5000, DataType.int64())),
        E.Compare("=", E.col("a.grp"), E.lit(7, DataType.int32())),
    )
    sel = ts.selectivity(pred, t)
    assert 0.005 <= sel <= 0.02


def test_equality_and_out_of_range(t):
    ts = collect_table_stats(t)
    sel_eq = ts.selectivity(
        E.Compare("=", E.col("x.grp"), E.lit(3, DataType.int32())), t
    )
    assert 0.01 <= sel_eq <= 0.04  # ~1/50
    sel_oor = ts.selectivity(
        E.Compare("=", E.col("x.k"), E.lit(1_000_000, DataType.int64())), t
    )
    assert sel_oor == 0.0


def test_varchar_selectivity_via_sorted_codes(t):
    ts = collect_table_stats(t)
    # name < 'c' matches ann, bob ~ 2/5 of rows
    sel = ts.selectivity(
        E.Compare("<", E.col("a.name"), E.lit("c", DataType.varchar(16))), t
    )
    assert 0.3 <= sel <= 0.5


def test_date_string_literal(t):
    ts = collect_table_stats(t)
    import datetime

    mid = (datetime.date(1970, 1, 1) + datetime.timedelta(days=18500)).isoformat()
    sel = ts.selectivity(
        E.Compare("<", E.col("a.day"), E.lit(mid, DataType.date())), t
    )
    assert 0.4 <= sel <= 0.6


def test_stats_manager_caches_and_invalidates(t):
    cat = {"t": t}
    sm = StatsManager(cat)
    ts1 = sm.table_stats("t")
    assert sm.table_stats("t") is ts1  # cached
    # new snapshot object -> recollect
    cat["t"] = Table(t.name, t.schema, dict(t.data), dict(t.dicts))
    ts2 = sm.table_stats("t")
    assert ts2 is not ts1
    assert sm.table_stats("missing") is None


def test_executor_estimates_use_stats(t):
    """Scan estimate ~ selectivity * nrows; group capacity ~ NDV not rows."""
    from oceanbase_tpu.engine.session import Session

    cat = {"t": t}
    sess = Session(cat)
    rs = sess.sql("select grp, count(*) as c from t where k < 1000 group by grp")
    assert rs.nrows == 50
    from oceanbase_tpu.sql.logical import Aggregate, Scan
    from oceanbase_tpu.sql.parser import parse

    planned = sess.planner.plan(parse(
        "select grp, count(*) as c from t where k < 1000 group by grp"))
    # scan estimate is ~1000, not nrows/4
    scan = planned.plan
    while not isinstance(scan, Scan):
        scan = next(iter(
            [getattr(scan, a) for a in ("child", "left") if hasattr(scan, a)]
        ))
    est = sess.executor._est_rows(scan)
    assert 500 <= est <= 2000
    # aggregate hash table sized near 50 groups, orders below 10k rows
    agg = planned.plan
    while not isinstance(agg, Aggregate):
        agg = agg.child
    params = sess.executor.seed_params(planned.plan)
    # sort-based group-by needs no hash-table capacity; the stats now size
    # the ROOT result-compaction buffer near the 50-group estimate instead
    from oceanbase_tpu.engine.executor import ROOT_COMPACT

    assert params.join_cap[ROOT_COMPACT] <= 4096


def test_zero_overflow_retries_on_tpch_q1_style(t):
    """With stats, capacity seeding should not need overflow recompiles."""
    from oceanbase_tpu.engine.session import Session

    cat = {"t": t}
    sess = Session(cat)
    rs = sess.sql(
        "select grp, sum(price) as s, count(*) as c from t group by grp "
        "order by grp"
    )
    assert rs.nrows == 50
    # run() tracks lifetime overflow recompiles on the prepared plan
    for entry in sess.plan_cache._entries.values() if hasattr(
            sess.plan_cache, "_entries") else []:
        assert entry.prepared.retries == 0


def test_packed_groupby_guard_survives_domain_drift():
    """Stats-packed group keys carry a runtime validity counter: values
    beyond the packed domain (stale stats after heavy DML) trigger the
    overflow-retry path which recompiles WITHOUT packing — results stay
    exact, never silently mis-grouped."""
    import numpy as np

    from oceanbase_tpu.core.dtypes import DataType, Field, Schema
    from oceanbase_tpu.core.table import Table
    from oceanbase_tpu.engine.executor import Executor
    from oceanbase_tpu.share.stats import StatsManager
    from oceanbase_tpu.sql.parser import parse
    from oceanbase_tpu.sql.planner import Planner

    I64 = DataType.int64()
    n = 4096
    rng = np.random.default_rng(9)
    a = rng.integers(0, 16, n)
    b = rng.integers(0, 8, n)
    t = Table.from_pydict(
        "t", Schema((Field("a", I64), Field("b", I64), Field("v", I64))),
        {"a": a, "b": b, "v": np.arange(n)})
    tables = {"t": t}
    ex = Executor(tables, stats=StatsManager(tables))
    pq = Planner(tables).plan(parse(
        "select a, b, sum(v) as s from t group by a, b"))
    prepared = ex.prepare(pq.plan)
    from oceanbase_tpu.engine.executor import PACK_GUARD_BASE

    assert any(i >= PACK_GUARD_BASE for i in prepared.overflow_nodes), \
        "packing not engaged"
    out = prepared.run()
    from oceanbase_tpu.core.column import batch_rows_normalized

    want = {}
    for ai, bi, vi in zip(a.tolist(), b.tolist(), range(n)):
        want[(ai, bi)] = want.get((ai, bi), 0) + vi
    got = batch_rows_normalized(out, pq.output_names)
    assert {(r[0], r[1]): r[2] for r in got} == want

    # drift FAR beyond the 4x headroom: same plan must retry to unpacked
    a2 = a.copy()
    a2[:64] = rng.integers(1 << 40, (1 << 40) + 1000, 64)
    t.data["a"] = a2
    ex.invalidate_table("t")
    out2 = prepared.run()
    assert prepared.retries >= 1, "guard did not trip"
    want2 = {}
    for ai, bi, vi in zip(a2.tolist(), b.tolist(), range(n)):
        want2[(ai, bi)] = want2.get((ai, bi), 0) + vi
    got2 = batch_rows_normalized(out2, pq.output_names)
    assert {(r[0], r[1]): r[2] for r in got2} == want2
