"""Mesh-sharded IVF kNN: the vector index's PX story.

The single-chip ANN kernel (engine/executor._emit_vector_topn) is two
matmuls + two top-ks over cluster-contiguous candidate windows. At mesh
scale the same shape shards perfectly: the permuted data matrix splits
into contiguous row blocks (one per shard — the cluster-contiguous
layout means a probed list's window touches at most a few blocks), the
tiny centroid table replicates, and every shard runs the IDENTICAL
probe: global centroid scan -> top-nprobe lists -> candidate window
positions. Each shard re-ranks only the window rows its block actually
holds (others masked to +inf), keeps a local top-k of (distance, global
position), and ONE ``all_gather`` of those k-candidate strips merges the
mesh — a final top-k over nsh*k rows replicates the exact answer
everywhere. The merge moves O(nsh * k) scalars, not candidate vectors:
the same narrowed-result discipline as the serving spine's O(k) D2H.

The result is bit-identical to the single-chip kernel: every candidate
row is re-ranked by exactly one shard with the same arithmetic, and the
final top-k sees the union of all windows. tests/test_vector_serving.py
pins sharded-vs-single-chip identity.

Collective accounting rides the standard SpmdLowering -> MeshPlan path
(spmd.py), so sharded ANN dispatches show up in the plan monitor /
sysstat "px collective" counters like any exchange."""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import SHARD_AXIS, mesh_signature, shard_map_compat
from .spmd import MeshPlan, SpmdLowering


@dataclass
class ShardedIvf:
    """One vector index resident across the mesh: the permuted data
    matrix row-sharded into contiguous blocks, probe metadata
    replicated, plus the jitted SPMD search program."""

    mesh: object
    nsh: int
    xs: object              # (nsh*rows_per_shard, d) row-sharded device
    cent: object            # (L, d) replicated
    offs: object            # (L,) replicated
    lens: object            # (L,) replicated
    perm: np.ndarray        # (n,) host — maps global positions to rowids
    max_list: int
    rows_per_shard: int
    nrows: int              # live rows (pre-padding)
    lowering: SpmdLowering = None
    _programs: dict = field(default_factory=dict)

    @property
    def mesh_plan(self) -> MeshPlan:
        return self.lowering.plan

    def device_bytes(self) -> int:
        """Whole-mesh resident footprint (governor unit is per-device:
        divide by nsh for one chip's share)."""
        return int(
            self.xs.dtype.itemsize * self.xs.size
            + self.cent.dtype.itemsize * self.cent.size
            + self.offs.dtype.itemsize * self.offs.size
            + self.lens.dtype.itemsize * self.lens.size)

    def search(self, q, k: int, nprobe: int):
        """Exact-merge sharded kNN probe. Returns (rowids, dists) as
        host arrays, rowids already mapped through the perm."""
        key = (int(k), int(nprobe))
        fn = self._programs.get(key)
        if fn is None:
            fn = self._programs[key] = self._compile(int(k), int(nprobe))
        dist, pos = fn(self.xs, self.cent, self.offs, self.lens,
                       jnp.asarray(q, jnp.float32))
        dist = np.asarray(dist)
        pos = np.asarray(pos)
        live = np.isfinite(dist)
        return self.perm[np.clip(pos, 0, len(self.perm) - 1)][live], \
            dist[live]

    def _compile(self, k: int, nprobe: int):
        nprobe = max(1, min(nprobe, int(self.lens.shape[0])))
        max_list = self.max_list
        rps = self.rows_per_shard
        kk = max(1, min(k, nprobe * max_list))
        lowering = self.lowering

        def local(xs, cent, offs, lens, q):
            # replayed per retrace: reset keeps MeshPlan exact
            lowering.reset()
            sid = jax.lax.axis_index(SHARD_AXIS)
            lo = (sid * rps).astype(jnp.int32)
            # global probe — identical on every shard (replicated inputs)
            cdist = jnp.sum(cent * cent, axis=1) - 2.0 * (cent @ q)
            _neg, probes = jax.lax.top_k(-cdist, nprobe)
            starts = offs[probes]
            ll = lens[probes]
            pos = (starts[:, None]
                   + jnp.arange(max_list, dtype=jnp.int32)).reshape(-1)
            valid = (jnp.arange(max_list, dtype=jnp.int32)[None, :]
                     < ll[:, None]).reshape(-1)
            # each candidate position belongs to exactly ONE shard's
            # contiguous block: re-rank it there, mask it everywhere else
            mine = valid & (pos >= lo) & (pos < lo + rps)
            li = jnp.clip(pos - lo, 0, max(rps - 1, 0))
            xv = xs[li]
            dist = jnp.sum(xv * xv, axis=1) - 2.0 * (xv @ q)
            dist = jnp.where(mine, dist, jnp.inf)
            negd, ti = jax.lax.top_k(-dist, kk)
            cand_pos = pos[ti]
            # merge: one strip of k (distance, position) pairs per shard
            lowering.note("ann merge", ncols=2, cap=kk, lanes=self.nsh,
                          collective="all_gather", legacy=False)
            gd = jax.lax.all_gather(-negd, SHARD_AXIS, tiled=True)
            gp = jax.lax.all_gather(cand_pos, SHARD_AXIS, tiled=True)
            neg2, t2 = jax.lax.top_k(-gd, kk)
            return -neg2, gp[t2]

        sharded = P(SHARD_AXIS)
        rep = P()
        return jax.jit(shard_map_compat(
            local,
            mesh=self.mesh,
            in_specs=(sharded, rep, rep, rep, rep),
            out_specs=(rep, rep),
            # replication of the merged top-k holds by construction
            # (all_gather then identical local math) but is not
            # statically inferable through the gather-index chain
            check_replication=False,
        ))


def shard_ivf(mesh, x: np.ndarray, idx) -> ShardedIvf:
    """Lay one built IvfIndex out across `mesh`: permuted rows split
    into equal contiguous blocks (padded with +inf rows so masked
    distances never win), metadata replicated."""
    nsh = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    x = np.asarray(x, dtype=np.float32)
    xs = x[idx.perm]
    n = xs.shape[0]
    rps = -(-n // nsh)  # ceil
    pad = nsh * rps - n
    if pad:
        # zero pad rows: list windows never reference positions >= n, so
        # pads are always masked out by `mine`; zeros (not inf) keep the
        # masked-lane dot products nan-free (0 * inf = nan)
        xs = np.concatenate(
            [xs, np.zeros((pad, xs.shape[1]), np.float32)])
    row_shard = NamedSharding(mesh, P(SHARD_AXIS))
    rep = NamedSharding(mesh, P())
    return ShardedIvf(
        mesh=mesh,
        nsh=nsh,
        xs=jax.device_put(xs, row_shard),
        cent=jax.device_put(np.asarray(idx.centroids, np.float32), rep),
        offs=jax.device_put(np.asarray(idx.offsets, np.int32), rep),
        lens=jax.device_put(np.asarray(idx.lengths, np.int32), rep),
        perm=np.asarray(idx.perm),
        max_list=int(idx.max_list),
        rows_per_shard=rps,
        nrows=n,
        lowering=SpmdLowering(mesh_signature(mesh), nsh),
    )
