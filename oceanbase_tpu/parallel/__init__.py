from .mesh import (
    SHARD_AXIS,
    make_mesh,
    replicated,
    row_sharding,
    shard_map_compat,
)
from .exchange import (
    broadcast_rows,
    dest_by_hash,
    dest_by_range,
    dest_round_robin,
    merge_partials,
    repartition,
)

__all__ = [
    "SHARD_AXIS",
    "make_mesh",
    "replicated",
    "row_sharding",
    "shard_map_compat",
    "broadcast_rows",
    "dest_by_hash",
    "dest_by_range",
    "dest_round_robin",
    "merge_partials",
    "repartition",
]
