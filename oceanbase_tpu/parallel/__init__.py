from .mesh import (
    SHARD_AXIS,
    make_mesh,
    mesh_signature,
    replicated,
    row_sharding,
    shard_map_compat,
)
from .exchange import (
    broadcast_rows,
    dest_by_hash,
    dest_by_range,
    dest_round_robin,
    merge_partials,
    repartition,
    ring_broadcast_rows,
)
from .spmd import (
    MeshExchange,
    MeshPlan,
    ShardedResidency,
    SpmdLowering,
    shard_put,
)

__all__ = [
    "SHARD_AXIS",
    "make_mesh",
    "mesh_signature",
    "replicated",
    "row_sharding",
    "shard_map_compat",
    "broadcast_rows",
    "dest_by_hash",
    "dest_by_range",
    "dest_round_robin",
    "merge_partials",
    "repartition",
    "ring_broadcast_rows",
    "MeshExchange",
    "MeshPlan",
    "ShardedResidency",
    "SpmdLowering",
    "shard_put",
]
