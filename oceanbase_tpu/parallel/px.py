"""PX: distributed plan execution as one SPMD program over a device mesh.

Reference surface: the parallel-execution component (sql/engine/px) — the
coordinator splits the plan into DFOs at TRANSMIT/RECEIVE pairs
(ObDfoMgr::do_split, ob_dfo_mgr.cpp:462), dispatches SQCs to nodes, workers
pull granules (ObGranuleIteratorOp) and rows cross DTL channels routed by
ObSliceIdxCalc; admission bounds cluster DOP (ObPxAdmission,
ob_px_target_mgr.h); join-filter pushdown ships build-side bloom filters to
probe-side scans (ob_px_bloom_filter_simd.cpp).

The TPU redesign collapses the DFO graph into ONE shard_map program:

  * DFO boundary      -> an exchange INSIDE the traced program
                         (all_to_all / all_gather collective, exchange.py)
  * granule iterator  -> static row-block shard of each table (device
                         sharding over the mesh axis IS the granule map)
  * SQC/worker threads-> the mesh devices themselves
  * DTL channel       -> collective lanes with static capacity + overflow
                         retry (no credit flow control: the collective is
                         the synchronization)
  * datahub rollup    -> psum/pmin/pmax partial-aggregate merges
  * join bloom filter -> build-side key bitset OR-reduced with psum,
                         applied to the probe mask BEFORE the all_to_all
                         (cuts exchanged rows, the pushdown's purpose)

Every intermediate carries a distribution state, the DFO data-layout
analog: SHARDED (rows split over the mesh axis) or REPLICATED (every
device holds all rows). Placement rules:

  scan -> SHARDED.  filter/project preserve.
  join: build(right) REPLICATED -> local; small build -> broadcast build;
        else hash-repartition both sides on the join keys.
  group-by: small-domain direct aggregation -> local partials + merge
        (REPLICATED out); generic hash group-by -> hash-repartition on the
        group keys (SHARDED out); scalar aggregate -> partials + merge.
  sort/limit/distinct: gather (REPLICATED), then identical local compute.
  root: gathered if still SHARDED.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.column import ColumnBatch
from ..core.dtypes import Schema
from ..engine.chunked import ChunkWindowMixin
from ..engine.executor import (
    DIRECT_GROUPBY_MAX_DOMAIN,
    Executor,
    _dict_domain,
    _number_nodes,
)
from ..expr import ir as E
from ..expr.compile import evaluate
from ..ops.hashing import hash32_combine, next_pow2
from ..sql.logical import (
    Aggregate,
    Distinct,
    JoinOp,
    Limit,
    Scan,
    SetOp,
    Sort,
    TopN,
    Window,
)
from .exchange import (
    broadcast_rows,
    dest_by_hash,
    repartition,
    ring_broadcast_rows,
)
from .mesh import SHARD_AXIS, mesh_signature, shard_map_compat
from .spmd import ShardedResidency, SpmdLowering, shard_put

SHARDED = "sharded"
REPLICATED = "replicated"

# synthesized PhysicalParams ids for exchange lanes (disjoint from plan
# node ids, which are small pre-order indexes)
_EXCH_BASE = 1_000_000


def _exch_id(nid: int, slot: int) -> int:
    return _EXCH_BASE + nid * 4 + slot


_AGG_CHILD, _JOIN_LEFT, _JOIN_RIGHT, _SORT_CHILD = 0, 1, 2, 3


class PxAdmission:
    """Cluster-wide DOP quota (ObPxAdmission / ObPxTargetMgr analog).

    acquire() grants up to `dop` workers, degrading to whatever quota
    remains (minimum 1, like the reference's min-DOP admission). When
    nothing is free the caller QUEUES (FIFO, condition-variable wait)
    up to `queue_timeout_s` — the reference's admission behavior
    (ob_px_admission.h waits on the target manager rather than failing
    a concurrent burst); only a timeout raises."""

    def __init__(self, target: int, queue_timeout_s: float = 10.0):
        self.target = target
        self.queue_timeout_s = queue_timeout_s
        self._used = 0
        self._lock = threading.Lock()
        self._free_cv = threading.Condition(self._lock)
        self._waiters = 0
        self.queued_total = 0  # observability: how often a burst queued

    def acquire(self, dop: int, timeout: float | None = None) -> int:
        deadline = time.monotonic() + (
            self.queue_timeout_s if timeout is None else timeout
        )
        with self._free_cv:
            first = True
            while self.target - self._used <= 0:
                if first:
                    self.queued_total += 1
                    self._waiters += 1
                    first = False
                remain = deadline - time.monotonic()
                timed_out = remain <= 0 or not self._free_cv.wait(remain)
                # a release can land between the wait timing out and the
                # lock reacquisition: re-check the predicate before
                # failing a query that would now be admissible
                if timed_out and self.target - self._used <= 0:
                    if not first:
                        self._waiters -= 1
                    raise RuntimeError(
                        f"PX admission: queue timeout "
                        f"({self._used}/{self.target} in use, "
                        f"{self._waiters} queued)"
                    )
            if not first:
                self._waiters -= 1
            granted = min(dop, self.target - self._used)
            self._used += granted
            return granted

    def release(self, granted: int) -> None:
        with self._free_cv:
            self._used = max(0, self._used - granted)
            self._free_cv.notify_all()


class PxExecutor(Executor):
    """Compiles logical plans into shard_map SPMD programs over a mesh."""

    # out-of-core streaming composes with PX: each chunk of the streamed
    # table dispatches as one shard_map program over the mesh; partials
    # merge on the (small) single-chip merge plan exactly as single-chip
    chunking_enabled = True
    # shard inputs are row slices — full-table fk_ranges would misindex
    # (PX compile never seeds clustered_aggs either; this is the belt)
    clustered_agg_enabled = False
    # likewise: dynamic-slice range pruning indexes whole-table columns
    scan_slice_enabled = False

    def make_chunk_source(self, stream_table: str, chunk_rows: int):
        # per-shard granularity: the chunk capacity must shard evenly
        unit = 1024 * self.nsh
        rows = -(-chunk_rows // unit) * unit
        src = _PxChunkSourceExecutor(
            self.catalog, stream_table, rows, mesh=self.mesh,
            unique_keys=self.unique_keys, stats=self.stats,
            default_rows_estimate=self.default_rows_estimate,
            broadcast_threshold=self.broadcast_threshold,
            join_bloom=self.join_bloom,
            bloom_max_bits=self.bloom_max_bits,
            hybrid_hash=self.hybrid_hash,
            broadcast_impl=self.broadcast_impl,
            tracer=self.tracer, metrics=self.metrics,
            access=self.access,
        )
        # the streamed path re-crosses the host every chunk: it must share
        # the observability channels so those hops are COUNTED, and the
        # residency ledger so resident side tables charge the governor once
        src.timeline = self.timeline
        src.governor = self.governor
        src.residency = self.residency
        return src

    def _affine_build_info(self, op):
        # inside shard_map every batch is a per-shard SLICE (and hash
        # exchanges reorder rows), so the storage-layout affinity the
        # direct-address join relies on does not hold: always sort-merge
        return None

    def __init__(self, catalog, mesh: Mesh, unique_keys=None,
                 default_rows_estimate=1 << 16,
                 broadcast_threshold: int = 1 << 16,
                 join_bloom: bool = True,
                 bloom_max_bits: int = 1 << 20,
                 hybrid_hash: "bool | str" = "auto",
                 broadcast_impl: str = "all_gather", stats=None,
                 device_budget=None, chunk_rows=None,
                 tracer=None, metrics=None, access=None):
        if stats is None:
            # histogram-backed cardinalities drive the exchange-method
            # choice (broadcast-vs-hash cost, skew-triggered hybrid hash)
            from ..share.stats import StatsManager

            stats = StatsManager(catalog)
        super().__init__(catalog, unique_keys=unique_keys,
                         default_rows_estimate=default_rows_estimate,
                         stats=stats, device_budget=device_budget,
                         chunk_rows=chunk_rows)
        self.mesh = mesh
        self.nsh = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        self.mesh_sig = mesh_signature(mesh)
        # BROADCAST lowering schedule: "all_gather" (bisection, default) or
        # "ring" (ppermute pipeline — flat per-link pressure on congested
        # torus axes). Bit-identical outputs; the MeshPlan records which
        # collective actually compiled.
        if broadcast_impl not in ("all_gather", "ring"):
            raise ValueError(f"unknown broadcast_impl {broadcast_impl!r}")
        self.broadcast_impl = broadcast_impl
        # partitioned residency: row sharding leaves each device holding
        # total/nsh bytes of every resident table — the ledger the memory
        # governor charges per device (register_sharded_residency)
        self.residency = ShardedResidency(self.nsh)
        # a plan's input bytes spread over nsh devices, so the per-device
        # budget admits nsh x the single-chip working set before the
        # prepare path degrades to chunk streaming (engine.Executor.prepare
        # multiplies its budget by this)
        self.budget_scale = self.nsh
        # per-compile mesh-plan recorder; bound (and reset) at trace entry
        # of the compiled program — jit traces lazily, so the MeshPlan
        # attached at prepare() time fills in during the first dispatch
        self._lowering: SpmdLowering | None = None
        self.broadcast_threshold = broadcast_threshold
        self.join_bloom = join_bloom
        self.bloom_max_bits = bloom_max_bits
        # skew-adaptive hybrid-hash joins (HYBRID_HASH_BROADCAST/RANDOM):
        # "auto" consults the optimizer histograms (the planner-side analog
        # of the reference's runtime sampling datahub decision,
        # ob_sql_define.h:393); True forces it, False disables
        self.hybrid_hash = hybrid_hash
        # workload repository (server/workload.TableAccessStats): observed
        # NDV / heavy-hitter evidence consulted by the skew heuristic
        # BEFORE the optimizer histograms — measured key frequencies beat
        # quantile-edge inference (JSPIM's sampled skew detection)
        self.access = access
        self._dist: dict[int, str] = {}
        # observability hooks (server/diag.Tracer + share/metrics registry).
        # Exchange helpers run INSIDE traced shard_map code, so accounting
        # happens host-side: once per compile at emission time (static
        # capacities/column counts are Python ints during tracing) and per
        # execute around the dispatch.
        self.tracer = tracer
        self.metrics = metrics
        # (ncols, lane_cap) per exchange emitted by the LAST compile —
        # execute() turns these into per-DFO worker spans
        self._exch_log: list[tuple[str, int, int]] = []

    def _note_exchange(self, kind: str, ncols: int, cap: int,
                       collective: str | None = None) -> None:
        """Host-side DTL accounting, called at TRACE time (once per
        compile): per-lane capacity x lane count x 8-byte columns is the
        shuffle volume the program moves each dispatch."""
        # broadcast all_gathers cap rows per shard; repartition is an
        # all_to_all over nsh^2 (src,dst) lanes of cap rows each
        lanes = self.nsh if kind == "broadcast" else self.nsh * self.nsh
        low = self._lowering
        if low is not None:
            # note() appends the legacy triple too — and _exch_log IS
            # lowering.legacy_log once the traced body bound it
            low.note(kind, ncols, cap, lanes, collective=collective)
        else:
            self._exch_log.append((kind, ncols, cap))
        m = self.metrics
        if m is not None:
            m.add("px exchanges compiled")
            m.add("px exchange rows capacity", cap * lanes)
            m.add("px exchange bytes capacity", ncols * cap * lanes * 8)

    def _note_merge(self, kind: str, ncols: int, cap: int,
                    elem_bytes: int = 8) -> None:
        """Record a reduction collective (psum/pmin/pmax families) in the
        mesh plan. These move O(groups) or O(bitset) data — tiny next to
        the row exchanges — so they stay out of the legacy exchange log
        (whose consumers size worker spans and peak-exchange bytes), but
        the mesh plan must show them: they ARE collectives the hot loop
        dispatches, and the zero-host-hop invariant counts them."""
        low = self._lowering
        if low is not None:
            low.note(kind, ncols, cap, self.nsh, collective="psum",
                     elem_bytes=elem_bytes, legacy=False)

    def execute(self, plan, max_retries: int = 3):
        """Coordinator-side execution wrapper: when a tracer is wired, the
        whole distributed query runs under one coordinator span and every
        compiled exchange gets a worker span nested inside it — so all PX
        spans share the coordinator's trace_id (the DTL channel-id ->
        trace propagation of the reference's full-link tracing)."""
        tr, m = self.tracer, self.metrics
        if tr is None and m is None:
            return super().execute(plan, max_retries)
        import time as _time
        from contextlib import nullcontext

        cm = (tr.span("px_coordinator", dop=self.nsh)
              if tr is not None else nullcontext())
        with cm as root:
            self._exch_log = []
            t0 = _time.perf_counter()
            prepared = self.prepare(plan)
            compile_s = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            out = prepared.run(max_retries)
            exec_s = _time.perf_counter() - t0
            if tr is not None:
                # per-DFO worker spans (one per exchange boundary the
                # compile emitted), inside the coordinator span. Read from
                # the prepared plan, not self._exch_log: the layout rides
                # the plan (filled at first-dispatch trace), so CACHED
                # plans — which never retrace — still get their spans.
                exch = getattr(prepared, "px_exchanges", self._exch_log)
                for i, (kind, ncols, cap) in enumerate(exch):
                    with tr.span("px_worker", dfo=i, exchange=kind,
                                 lane_cap=cap, cols=ncols):
                        pass
                root.tags["compile_us"] = int(compile_s * 1e6)
                root.tags["exec_us"] = int(exec_s * 1e6)
            if m is not None:
                m.add("px executions")
                m.observe("px compile", compile_s)
                m.observe("px execute", exec_s)
                m.wait("px dispatch", exec_s)
            mp = getattr(prepared, "mesh_plan", None)
            if mp is not None and mp.total_ops:
                if m is not None:
                    for coll, cnt in mp.ops_by_collective().items():
                        m.add(f"px collective {coll}", cnt)
                    m.add("px collective bytes", mp.total_bytes)
                tl = self.timeline
                if tl is not None:
                    tl.record_collective(mp.total_ops, mp.total_bytes)
        return out

    def prepare(self, plan):
        """Compile + attach the mesh plan to the prepared plan, so a
        session executing a CACHED PX plan can still emit per-DFO worker
        spans and per-collective counters (the exchange layout is a
        compile-time artifact; re-deriving it per execution would mean
        re-tracing).

        The attachment is BY REFERENCE, not a snapshot: jax.jit traces at
        first dispatch, so the emission-site notes land in the
        SpmdLowering compile() created only when the program first runs.
        The prepared plan and the traced closure share the same MeshPlan
        object; it fills in during dispatch and every later consumer
        (session folds, artifact save) reads the populated layout."""
        self._lowering = None
        prepared = super().prepare(plan)
        self.sync_prepared(prepared)
        return prepared

    def sync_prepared(self, prepared) -> None:
        """(Re)attach the current compile's mesh plan to a prepared plan —
        called from prepare() and again by PreparedPlan.recompile(), whose
        overflow-retry recompiles build a fresh SpmdLowering that the
        cached plan must follow."""
        low = self._lowering
        if low is None:
            # chunk-streamed plans compile inside the chunk-source
            # executor; the outer plan keeps an empty mesh plan (its
            # per-chunk programs are accounted by the source executor)
            low = SpmdLowering(self.mesh_sig, self.nsh)
        prepared.mesh_plan = low.plan
        prepared.px_exchanges = low.legacy_log
        prepared.px_nsh = self.nsh
        prepared.mesh_sig = self.mesh_sig

    # ------------------------------------------------------------ inputs
    def table_batch(self, name: str, cols: tuple[str, ...]):
        """Raw sharded input: cols/valid/sel arrays padded to a multiple of
        nsh*1024 and placed with row sharding (the granule map)."""
        is_private = getattr(self.catalog, "is_private", None)
        if is_private is not None and is_private(name):
            # tx-private view: shard + upload fresh, NEVER through the
            # shared cache (same isolation contract as the base executor).
            # No residency charge: the view dies with the statement.
            return self._shard_upload(name, cols, resident=False)
        key = (name, cols)
        if key not in self._batch_cache:
            self._batch_cache[key] = self._shard_upload(name, cols)
        return self._batch_cache[key]

    def invalidate_table(self, name: str) -> None:
        super().invalidate_table(name)
        self.residency.discharge(name)

    def _shard_upload(self, name: str, cols: tuple[str, ...],
                      resident: bool = True):
        from ..core.column import make_batch

        t = self.catalog[name]
        sub_schema = Schema(
            tuple(f for f in t.schema.fields if f.name in cols)
        )
        unit = 1024 * self.nsh
        cap = max(unit, -(-(t.nrows or 1) // unit) * unit)
        b = make_batch(
            {c: t.data[c] for c in sub_schema.names()},
            sub_schema,
            {c: d for c, d in t.dicts.items() if c in cols},
            capacity=cap,
            valid={c: v for c, v in t.valid.items() if c in cols},
        )
        raw, nbytes = shard_put(self.mesh, b)
        self.h2d_bytes += nbytes
        if resident:
            # partitioned residency: each device of the mesh now holds
            # nbytes/nsh of this table; the governor charges per device
            self.residency.charge(name, nbytes)
        tl = self.timeline
        if tl is not None:
            tl.record_transfer(nbytes)
        m = self.metrics
        if m is not None:
            m.add("px sharded upload bytes", nbytes)
        return raw

    # ------------------------------------------------------- capacities
    def seed_params(self, plan):
        params = super().seed_params(plan)
        nodes = _number_nodes(plan)
        est = self._est_rows

        def lane_cap(rows: float) -> int:
            # per (src,dst) lane of an all_to_all: expected rows/nsh^2
            # with 2x skew headroom
            c = int(rows * 2 / (self.nsh * self.nsh)) + 512
            return -(-c // 128) * 128

        for nid, op in nodes.items():
            if isinstance(op, JoinOp) and op.left_keys:
                params.exchange_cap[_exch_id(nid, _JOIN_LEFT)] = lane_cap(
                    est(op.left))
                params.exchange_cap[_exch_id(nid, _JOIN_RIGHT)] = lane_cap(
                    est(op.right))
            if isinstance(op, Aggregate) and (
                op.group_keys
                # scalar DISTINCT (and approx_ndv) aggs exchange by the
                # distinct argument
                or any(a[3] or a[1] == "approx_ndv" for a in op.aggs)
            ):
                params.exchange_cap[_exch_id(nid, _AGG_CHILD)] = lane_cap(
                    est(op.child))
            if isinstance(op, Sort) and self._sortable_by_range(op):
                params.exchange_cap[_exch_id(nid, _SORT_CHILD)] = lane_cap(
                    est(op.child))
            if isinstance(op, Distinct):
                params.exchange_cap[_exch_id(nid, _AGG_CHILD)] = lane_cap(
                    est(op.child))
            if isinstance(op, SetOp) and not (op.kind == "union" and op.all):
                # UNION ALL never exchanges; every other set op
                # co-partitions both sides by whole-row hash
                params.exchange_cap[_exch_id(nid, _JOIN_LEFT)] = lane_cap(
                    est(op.left))
                params.exchange_cap[_exch_id(nid, _JOIN_RIGHT)] = lane_cap(
                    est(op.right))
            if isinstance(op, Window) and self._window_common_pk(op):
                params.exchange_cap[_exch_id(nid, _AGG_CHILD)] = lane_cap(
                    est(op.child))
        return params

    @staticmethod
    def _sortable_by_range(op: Sort) -> bool:
        """RANGE exchange needs an integer-typed leading sort key (ints,
        dates, dict codes, scaled decimals — everything the engine stores
        as integers)."""
        from ..expr.compile import infer_type
        from ..sql.logical import output_schema

        try:
            dt = infer_type(op.keys[0][0], output_schema(op.child))
        except Exception:
            return False
        return np.issubdtype(dt.storage_np, np.integer)

    @staticmethod
    def _window_common_pk(op: Window):
        """The shared partition-key tuple of all window specs, or None.
        With a common non-empty PARTITION BY, hash repartitioning on it is
        semantics-preserving (each partition lands whole on one shard) —
        the reference's range-dist parallel window (datahub winbuf) analog."""
        pks = {pk for _n, _f, _a, pk, _ok, _x in op.funcs}
        if len(pks) == 1:
            pk = next(iter(pks))
            if pk:
                return pk
        return None

    # -------------------------------------------------------- exchanges
    def _gather_batch(self, b: ColumnBatch) -> ColumnBatch:
        """GATHER/BROADCAST: replicate all rows on every shard, via
        all_gather (bisection) or the ppermute ring per broadcast_impl."""
        ring = self.broadcast_impl == "ring"
        self._note_exchange("broadcast", len(b.cols) + len(b.valid),
                            int(b.sel.shape[0]),
                            collective="ppermute" if ring else "all_gather")
        payload = {f"c:{n}": a for n, a in b.cols.items()}
        payload.update({f"v:{n}": a for n, a in b.valid.items()})
        if ring:
            out, mask = ring_broadcast_rows(payload, b.sel, self.nsh)
        else:
            out, mask = broadcast_rows(payload, b.sel)
        return ColumnBatch(
            cols={n: out[f"c:{n}"] for n in b.cols},
            valid={n: out[f"v:{n}"] for n in b.valid},
            sel=mask,
            nrows=jnp.sum(mask, dtype=jnp.int64),
            schema=b.schema,
            dicts=b.dicts,
        )

    def _exchange_dest(self, b: ColumnBatch, dest, cap: int):
        """Redistribute rows of a batch to per-row dest shards (all_to_all)."""
        self._note_exchange("repartition", len(b.cols) + len(b.valid), cap)
        payload = {f"c:{n}": a for n, a in b.cols.items()}
        payload.update({f"v:{n}": a for n, a in b.valid.items()})
        out, mask, ovf = repartition(payload, b.sel, dest, self.nsh, cap)
        nb = ColumnBatch(
            cols={n: out[f"c:{n}"] for n in b.cols},
            valid={n: out[f"v:{n}"] for n in b.valid},
            sel=mask,
            nrows=jnp.sum(mask, dtype=jnp.int64),
            schema=b.schema,
            dicts=b.dicts,
        )
        return nb, ovf

    def _exchange_hash(self, b: ColumnBatch, key_exprs, cap: int):
        """HASH distribution: co-partition rows by key hash (all_to_all)."""
        keys = [evaluate(e, b)[0] for e in key_exprs]
        return self._exchange_dest(b, dest_by_hash(keys, self.nsh), cap)

    def _concat_batches(self, a: ColumnBatch, b: ColumnBatch) -> ColumnBatch:
        """Row-concatenate two same-schema batches (static capacities add)."""
        cols = {n: jnp.concatenate([a.cols[n], b.cols[n]]) for n in a.cols}
        valid = {n: jnp.concatenate([a.valid[n], b.valid[n]]) for n in a.valid}
        sel = jnp.concatenate([a.sel, b.sel])
        return ColumnBatch(
            cols=cols, valid=valid, sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=a.schema, dicts=a.dicts,
        )

    def _hybrid_exchange(self, probe: ColumnBatch, probe_keys,
                         build: ColumnBatch, build_keys,
                         cap_probe: int, cap_build: int):
        """HYBRID_HASH_BROADCAST/RANDOM: skew-adaptive repartition.

        The reference samples probe keys through the datahub and routes
        popular values BROADCAST (build side) / RANDOM-local (probe side)
        while normal values go HASH (ob_sql_define.h:393, hybrid-hash with
        the dynamic-sample msg). SPMD analog: a psum'd hash-bucket
        histogram of probe keys picks the popular buckets identically on
        every shard; popular probe rows stay local, popular build rows
        all_gather, normal rows of both sides all_to_all by key hash."""
        hb = 4096
        # two psum'd histograms (probe + build) pick the hot buckets
        self._note_merge("skew_histogram", 2, hb)
        pk = [evaluate(e, probe)[0] for e in probe_keys]
        ph = (hash32_combine(pk) % jnp.uint32(hb)).astype(jnp.int32)
        bk = [evaluate(e, build)[0] for e in build_keys]
        bh = (hash32_combine(bk) % jnp.uint32(hb)).astype(jnp.int32)

        def hot_buckets(h, sel):
            cnt = jnp.zeros(hb, dtype=jnp.int64).at[
                jnp.where(sel, h, hb)
            ].add(1, mode="drop")
            cnt = lax.psum(cnt, SHARD_AXIS)
            # a bucket is popular when its rows would overload one shard's
            # fair share by 2x
            return cnt > jnp.maximum(jnp.sum(cnt) * 2 // self.nsh, 1)

        # skew on EITHER side forces the hybrid route for that key: a
        # heavily-duplicated build key would overload its hash lane exactly
        # like a popular probe key would
        popular = hot_buckets(ph, probe.sel) | hot_buckets(bh, build.sel)
        p_pop = popular[ph] & probe.sel

        probe_norm, ox_p = self._exchange_hash(
            probe.with_sel(probe.sel & ~p_pop), probe_keys, cap_probe)
        probe_loc = probe.with_sel(p_pop)
        # align capacities: exchanged batch is nsh*cap rows; local popular
        # rows keep their original capacity — concat handles both
        new_probe = self._concat_batches(probe_norm, probe_loc)

        b_pop = popular[bh] & build.sel
        build_norm, ox_b = self._exchange_hash(
            build.with_sel(build.sel & ~b_pop), build_keys, cap_build)
        build_bc = self._gather_batch(build.with_sel(b_pop))
        new_build = self._concat_batches(build_norm, build_bc)
        return new_probe, new_build, ox_p, ox_b

    def _bloom_prefilter(self, probe: ColumnBatch, probe_keys, build: ColumnBatch,
                         build_keys, est_build: float) -> ColumnBatch:
        """Join-filter pushdown: OR-reduce a build-side key bitset across
        shards, drop probe rows that cannot match BEFORE the exchange."""
        m = min(self.bloom_max_bits, next_pow2(max(int(4 * est_build), 1024)))
        self._note_merge("bloom", 1, m, elem_bytes=4)
        bk = [evaluate(e, build)[0] for e in build_keys]
        h = (hash32_combine(bk) % jnp.uint32(m)).astype(jnp.int32)
        bits = jnp.zeros(m, dtype=jnp.int32).at[
            jnp.where(build.sel, h, m)
        ].set(1, mode="drop")
        bits = lax.psum(bits, SHARD_AXIS) > 0
        pk = [evaluate(e, probe)[0] for e in probe_keys]
        ph = (hash32_combine(pk) % jnp.uint32(m)).astype(jnp.int32)
        return probe.with_sel(probe.sel & bits[ph])

    # ------------------------------------------------------- emission
    def _emit_node(self, op, inputs, emit, params, id_of):
        nid = id_of[id(op)]

        if isinstance(op, Scan):
            out, ovf = super()._emit_node(op, inputs, emit, params, id_of)
            self._dist[id(op)] = SHARDED
            return out, ovf

        if isinstance(op, JoinOp):
            return self._emit_join_px(op, nid, inputs, emit, params, id_of)

        if isinstance(op, Aggregate):
            return self._emit_agg_px(op, nid, inputs, emit, params, id_of)

        if isinstance(op, Sort):
            return self._emit_sort_px(op, nid, inputs, emit, params, id_of)

        if isinstance(op, TopN):
            # two-phase top-n: per-shard top (n+offset) local rows, gather
            # the small survivors, final top-n (the merge-sort-receive
            # coordinator analog, ob_px_ms_receive_vec_op.h)
            child, covf = emit(op.child, inputs)
            if self._dist[id(op.child)] == SHARDED:
                local = self._topn_batch(
                    child, op.keys, op.n, op.offset, apply_offset=False)
                gathered = self._gather_batch(local)
                out = self._topn_batch(gathered, op.keys, op.n, op.offset)
            else:
                out = self._topn_batch(child, op.keys, op.n, op.offset)
            self._dist[id(op)] = REPLICATED
            return out, covf

        if isinstance(op, Window):
            return self._emit_window_px(op, nid, inputs, emit, params, id_of)

        if isinstance(op, Limit):
            # per-shard prelimit + compacted gather: moves O(n + offset)
            # rows per shard, never the relation
            child, covf = emit(op.child, inputs)
            if self._dist[id(op.child)] == SHARDED:
                from ..engine.executor import compact_batch

                k = op.n + op.offset
                pos = jnp.cumsum(child.sel.astype(jnp.int64)) - 1
                local = child.with_sel(child.sel & (pos < k))
                cap2 = min(child.capacity, max(8, -(-k // 8) * 8))
                local, _oc = compact_batch(local, cap2)  # k <= cap2: no ovf
                child = self._gather_batch(local)
                covf = dict(covf)
            out, ovf = super()._emit_node(
                op, inputs, _override(emit, op.child, (child, covf)),
                params, id_of)
            self._dist[id(op)] = REPLICATED
            return out, ovf

        if isinstance(op, Distinct):
            # hash-repartition on the whole row, then each shard owns its
            # value space: local dedup is globally exact and no shard ever
            # holds the relation (the reference's HASH distinct,
            # ObPQDistributeMethod::HASH)
            child, covf = emit(op.child, inputs)
            cd = self._dist[id(op.child)]
            exch = _exch_id(nid, _AGG_CHILD)
            if (
                cd == SHARDED
                and exch in params.exchange_cap
                and self._est_rows(op.child) > self.broadcast_threshold
            ):
                keys = self._row_hash_keys(child)
                child2, xovf = self._exchange_dest(
                    child, dest_by_hash(keys, self.nsh),
                    params.exchange_cap[exch])
                out, ovf = super()._emit_node(
                    op, inputs, _override(emit, op.child, (child2, covf)),
                    params, id_of)
                ovf = dict(ovf)
                ovf[exch] = xovf
                self._dist[id(op)] = SHARDED
                return out, ovf
            if cd == SHARDED:
                child = self._gather_batch(child)
            out, ovf = super()._emit_node(
                op, inputs, _override(emit, op.child, (child, covf)),
                params, id_of)
            self._dist[id(op)] = REPLICATED
            return out, ovf

        if isinstance(op, SetOp):
            return self._emit_setop_px(op, nid, inputs, emit, params, id_of)

        # Filter / Project: local, distribution-preserving
        out, ovf = super()._emit_node(op, inputs, emit, params, id_of)
        child = getattr(op, "child", None)
        self._dist[id(op)] = self._dist[id(child)] if child is not None else SHARDED
        return out, ovf

    # ---- set operations --------------------------------------------------
    def _row_hash_keys(self, b: ColumnBatch):
        """Whole-row hash key columns with set-op NULL normalization
        (validity bits join as int32 so hash32_combine sees integers)."""
        keys = self._setop_key_cols(b.cols, b.valid, b.schema)
        return [
            k.astype(jnp.int32) if k.dtype == jnp.bool_ else k for k in keys
        ]

    def _copartition_side(self, b: ColumnBatch, dist: str, cap: int):
        """Bring one promoted set-op side onto the whole-row hash
        partitioning. SHARDED: all_to_all exchange. REPLICATED: free —
        every shard already holds all rows, so each just keeps the ones
        hashing to itself (a mask, no collective)."""
        dest = dest_by_hash(self._row_hash_keys(b), self.nsh)
        if dist == REPLICATED:
            me = lax.axis_index(SHARD_AXIS).astype(dest.dtype)
            return b.with_sel(b.sel & (dest == me)), None
        return self._exchange_dest(b, dest, cap)

    def _emit_setop_px(self, op: SetOp, nid, inputs, emit, params, id_of):
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ld, rd = self._dist[id(op.left)], self._dist[id(op.right)]
        ovf = {**lovf, **rovf}
        lb, rb, out_schema, dicts = self._setop_promote(op, left, right)

        if op.kind == "union" and op.all:
            # pure concatenation: SHARDED++SHARDED stays sharded with no
            # exchange; a REPLICATED side spreads by row index so its rows
            # exist exactly once globally
            if ld == rd == REPLICATED:
                out, ovf = self._setop_combine(
                    op, lb, rb, out_schema, dicts, ovf)
                self._dist[id(op)] = REPLICATED
                return out, ovf
            me = lax.axis_index(SHARD_AXIS)
            if ld == REPLICATED:
                ridx = jnp.arange(lb.capacity) % self.nsh
                lb = lb.with_sel(lb.sel & (ridx == me))
            if rd == REPLICATED:
                ridx = jnp.arange(rb.capacity) % self.nsh
                rb = rb.with_sel(rb.sel & (ridx == me))
            out, ovf = self._setop_combine(op, lb, rb, out_schema, dicts, ovf)
            self._dist[id(op)] = SHARDED
            return out, ovf

        cap_l = params.exchange_cap.get(_exch_id(nid, _JOIN_LEFT))
        cap_r = params.exchange_cap.get(_exch_id(nid, _JOIN_RIGHT))
        big = (
            self._est_rows(op.left) + self._est_rows(op.right)
            > self.broadcast_threshold
        )
        if big and cap_l is not None and cap_r is not None \
                and (ld == SHARDED or rd == SHARDED):
            # co-partition both sides by whole-row hash: every equal row
            # lands on one shard, so the local dedup/bag kernels are
            # globally exact and the output stays SHARDED
            lb2, xl = self._copartition_side(lb, ld, cap_l)
            rb2, xr = self._copartition_side(rb, rd, cap_r)
            out, ovf = self._setop_combine(op, lb2, rb2, out_schema, dicts, ovf)
            ovf = dict(ovf)
            if xl is not None:
                ovf[_exch_id(nid, _JOIN_LEFT)] = xl
            if xr is not None:
                ovf[_exch_id(nid, _JOIN_RIGHT)] = xr
            self._dist[id(op)] = SHARDED
            return out, ovf

        if ld == SHARDED:
            lb = self._gather_batch(lb)
        if rd == SHARDED:
            rb = self._gather_batch(rb)
        out, ovf = self._setop_combine(op, lb, rb, out_schema, dicts, ovf)
        self._dist[id(op)] = REPLICATED
        return out, ovf

    # ---- sort / window --------------------------------------------------
    def _emit_sort_px(self, op: Sort, nid, inputs, emit, params, id_of):
        """Large SHARDED sorts exchange by RANGE on the leading key (the
        reference's ObPQDistributeMethod::RANGE, ob_sql_define.h:390):
        every shard gets one contiguous key range, sorts locally, and the
        shard-order concatenation at gather time IS the global order —
        nothing ever holds the whole relation. Small or already-replicated
        inputs keep the gather-then-sort path."""
        from .exchange import dest_by_range, sample_range_bounds

        child, covf = emit(op.child, inputs)
        cd = self._dist[id(op.child)]
        exch = _exch_id(nid, _SORT_CHILD)
        use_range = (
            cd == SHARDED
            and exch in params.exchange_cap
            and self._est_rows(op.child) > self.broadcast_threshold
        )
        if not use_range:
            if cd == SHARDED:
                child = self._gather_batch(child)
            out, ovf = super()._emit_node(
                op, inputs, _override(emit, op.child, (child, covf)),
                params, id_of)
            self._dist[id(op)] = REPLICATED
            return out, ovf

        key_expr, desc0 = op.keys[0]
        kv = evaluate(key_expr, child)[0]
        self._note_merge("range_sample", 1, 4096)
        bounds = sample_range_bounds(kv, child.sel, self.nsh)
        dest = dest_by_range(kv.astype(jnp.int64), bounds)
        if desc0:
            # shard 0 must hold the HIGHEST range so the gathered
            # concatenation reads in descending order
            dest = (self.nsh - 1) - dest
        child2, xovf = self._exchange_dest(
            child, dest, params.exchange_cap[exch])
        out, ovf = super()._emit_node(
            op, inputs, _override(emit, op.child, (child2, covf)),
            params, id_of)
        ovf = dict(ovf)
        ovf[exch] = xovf
        # rows stay sharded; each shard holds one globally-contiguous,
        # locally-sorted range (ties colocate: equal keys share a dest)
        self._dist[id(op)] = SHARDED
        return out, ovf

    def _emit_window_px(self, op: Window, nid, inputs, emit, params, id_of):
        """Windows with a common PARTITION BY hash-repartition on it — each
        partition lands whole on one shard, so per-shard evaluation is
        exact and O(rows/shard). Mixed/empty partition keys gather."""
        child, covf = emit(op.child, inputs)
        cd = self._dist[id(op.child)]
        exch = _exch_id(nid, _AGG_CHILD)
        pk = self._window_common_pk(op)
        if (
            cd == SHARDED
            and pk is not None
            and exch in params.exchange_cap
            and self._est_rows(op.child) > self.broadcast_threshold
        ):
            child2, xovf = self._exchange_hash(
                child, list(pk), params.exchange_cap[exch])
            out, ovf = super()._emit_node(
                op, inputs, _override(emit, op.child, (child2, covf)),
                params, id_of)
            ovf = dict(ovf)
            ovf[exch] = xovf
            self._dist[id(op)] = SHARDED
            return out, ovf
        if cd == SHARDED:
            child = self._gather_batch(child)
        out, ovf = super()._emit_node(
            op, inputs, _override(emit, op.child, (child, covf)),
            params, id_of)
        self._dist[id(op)] = REPLICATED
        return out, ovf

    # ---- joins ----------------------------------------------------------
    def _skewed_key(self, side_op, keys) -> bool:
        """Histogram skew signal for auto hybrid-hash: a value repeated
        across r consecutive equi-height bucket edges carries >= (r-1)/N
        of the rows; when one value would overload a shard's fair lane by
        2x, plain hash distribution will hot-spot that shard."""
        from ..share.stats import N_BUCKETS
        from ..sql.logical import Filter, Project, Scan

        if len(keys) != 1 or self.stats is None:
            return False
        e = keys[0]
        name = e.name if isinstance(e, E.ColRef) else None
        if name is None:
            return False
        node = side_op
        while isinstance(node, (Filter, Project)):
            if isinstance(node, Project):
                nxt = dict(node.exprs).get(name)
                if not isinstance(nxt, E.ColRef):
                    return False
                name = nxt.name
            node = node.child
        if not isinstance(node, Scan) or "." not in name:
            return False
        alias, col = name.split(".", 1)
        if alias != node.alias:
            return False
        # runtime evidence first: the workload repository's measured
        # NDV / heavy-hitter fraction for this key column. One observed
        # value carrying >= 2/nsh of the rows overloads its shard's fair
        # lane 2x under plain hash distribution — exactly the condition
        # the quantile-edge walk below infers, but measured, not inferred
        if self.access is not None:
            ev = self.access.key_evidence(
                node.table, col, self.catalog.get(node.table))
            if ev is not None and ev[1] >= 2.0 / self.nsh:
                return True
        ts = self.stats.table_stats(node.table)
        cs = ts.cols.get(col) if ts is not None else None
        if cs is None or cs.edges is None:
            return False
        edges = np.asarray(cs.edges)
        # longest run of identical consecutive edges
        eq = edges[1:] == edges[:-1]
        best = run = 0
        for x in eq:
            run = run + 1 if x else 0
            best = max(best, run)
        hot_frac = best / N_BUCKETS
        return hot_frac >= 2.0 / self.nsh

    def _emit_join_px(self, op, nid, inputs, emit, params, id_of):
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ld, rd = self._dist[id(op.left)], self._dist[id(op.right)]
        ovf = {**lovf, **rovf}

        # choose distribution method (the optimizer's exchange allocation)
        if op.kind == "full" and (ld == SHARDED or rd == SHARDED):
            # a broadcast build would duplicate unmatched-right rows on
            # every shard: FULL joins must co-partition both sides
            method = "hash" if op.left_keys else "gather_both"
        elif rd == REPLICATED:
            method = "local"  # build already everywhere; probe drives output
        elif not op.left_keys:
            method = "broadcast"  # cross join: replicate the build side
        elif ld == REPLICATED:
            method = "broadcast"  # make both sides replicated
        elif self._est_rows(op.right) <= self.broadcast_threshold or (
            # cost model: broadcast ships est_r to every shard; hash moves
            # each row of both sides once (ObLogPlan's exchange costing)
            self._est_rows(op.right) * (self.nsh - 1)
            <= self._est_rows(op.left)
        ):
            method = "broadcast"
        else:
            method = "hash"

        if method == "hash":
            # bloom pushdown is only sound where dropping non-matching
            # probe rows is a no-op: inner and semi joins (an anti/left
            # join must KEEP unmatched probe rows)
            if self.join_bloom and op.kind in ("inner", "cross", "semi"):
                left = self._bloom_prefilter(
                    left, op.left_keys, right, op.right_keys,
                    self._est_rows(op.right))
            cap_l = params.exchange_cap[_exch_id(nid, _JOIN_LEFT)]
            cap_r = params.exchange_cap[_exch_id(nid, _JOIN_RIGHT)]
            use_hybrid = op.kind == "inner" and (
                self.hybrid_hash is True
                or (
                    self.hybrid_hash == "auto"
                    and (
                        self._skewed_key(op.left, op.left_keys)
                        or self._skewed_key(op.right, op.right_keys)
                    )
                )
            )
            if use_hybrid:
                left, right, xl, xr = self._hybrid_exchange(
                    left, op.left_keys, right, op.right_keys, cap_l, cap_r)
            else:
                left, xl = self._exchange_hash(left, op.left_keys, cap_l)
                right, xr = self._exchange_hash(right, op.right_keys, cap_r)
            ovf = dict(ovf)
            ovf[_exch_id(nid, _JOIN_LEFT)] = xl
            ovf[_exch_id(nid, _JOIN_RIGHT)] = xr
            out_dist = SHARDED
        elif method == "broadcast":
            right = self._gather_batch(right)
            out_dist = ld
        elif method == "gather_both":
            if ld == SHARDED:
                left = self._gather_batch(left)
            if rd == SHARDED:
                right = self._gather_batch(right)
            out_dist = REPLICATED
        else:
            out_dist = ld

        emit2 = _override(
            _override(emit, op.left, (left, {})), op.right, (right, {}))
        out, jovf = super()._emit_join(op, nid, inputs, emit2, params)
        ovf.update({k: v for k, v in jovf.items() if k not in ovf})
        self._dist[id(op)] = out_dist
        return out, ovf

    # ---- aggregation -----------------------------------------------------
    def _emit_agg_px(self, op, nid, inputs, emit, params, id_of):
        child, covf = emit(op.child, inputs)
        cd = self._dist[id(op.child)]

        if cd == REPLICATED:
            out, ovf = super()._emit_aggregate(
                op, nid, inputs, _override(emit, op.child, (child, covf)),
                params)
            self._dist[id(op)] = REPLICATED
            return out, ovf

        domains = [_dict_domain(child, e) for _, e in op.group_keys]
        direct = (
            bool(op.group_keys)
            and all(d is not None for d in domains)
            and int(np.prod([d for d in domains])) <= DIRECT_GROUPBY_MAX_DOMAIN
        )

        # DISTINCT aggregates: a shard's partial over its local first
        # occurrences double-counts values present on other shards, so the
        # rows must be colocated by the dedup domain BEFORE aggregating.
        # Grouped: the generic hash-repartition on group keys below already
        # does that. Scalar: repartition on the (single) distinct argument,
        # then partials are disjoint and psum-merge correctly.
        # approx_ndv joins the distinct-colocation set: once rows are
        # hash-colocated by the argument, each shard sketches a DISJOINT
        # value set and the estimates psum-merge (union of disjoint sets)
        distinct_args = {a[2] for a in op.aggs if a[3] or a[1] == "approx_ndv"}
        if distinct_args and not op.group_keys:
            if len(distinct_args) == 1:
                cap = params.exchange_cap[_exch_id(nid, _AGG_CHILD)]
                child, xovf = self._exchange_hash(
                    child, [next(iter(distinct_args))], cap)
                covf = dict(covf)
                covf[_exch_id(nid, _AGG_CHILD)] = xovf
            else:
                # two different distinct domains cannot both colocate by
                # one exchange: replicate (rare shape; correct, not fast)
                child = self._gather_batch(child)
                out, ovf = super()._emit_aggregate(
                    op, nid, inputs,
                    _override(emit, op.child, (child, covf)), params)
                self._dist[id(op)] = REPLICATED
                return out, ovf
        elif distinct_args:
            direct = False  # partials+psum would double-count: repartition

        if direct or not op.group_keys:
            # local partials + datahub-rollup merge: moves O(groups), not
            # O(rows) — the right plan for small-domain group-bys (Q1) and
            # scalar aggregates (Q6)
            out, ovf = super()._emit_aggregate(
                op, nid, inputs, _override(emit, op.child, (child, covf)),
                params)
            # datahub-rollup merge: one reduction over the partial-agg
            # columns + sel/valid masks (O(groups) data, not O(rows))
            self._note_merge(
                "merge", len(out.cols) + len(out.valid) + 1,
                int(out.sel.shape[0]))
            merged = dict(out.cols)
            for name, fn, _arg, _d in op.aggs:
                col = out.cols[name]
                if fn in ("sum", "count", "approx_ndv"):
                    merged[name] = lax.psum(col, SHARD_AXIS)
                elif fn == "min":
                    merged[name] = lax.pmin(col, SHARD_AXIS)
                elif fn == "max":
                    merged[name] = lax.pmax(col, SHARD_AXIS)
                else:
                    raise NotImplementedError(f"PX merge for {fn}")
            sel = lax.psum(out.sel.astype(jnp.int32), SHARD_AXIS) > 0
            valid = {
                n: lax.psum(v.astype(jnp.int32), SHARD_AXIS) > 0
                for n, v in out.valid.items()
            }
            out = replace(
                out, cols=merged, valid=valid, sel=sel,
                nrows=jnp.sum(sel, dtype=jnp.int64),
            )
            self._dist[id(op)] = REPLICATED
            return out, ovf

        # generic hash group-by: co-partition rows on the group keys, then
        # each shard owns its key space entirely
        cap = params.exchange_cap[_exch_id(nid, _AGG_CHILD)]
        child2, xovf = self._exchange_hash(
            child, [e for _, e in op.group_keys], cap)
        out, ovf = super()._emit_aggregate(
            op, nid, inputs, _override(emit, op.child, (child2, covf)), params)
        ovf = dict(ovf)
        ovf[_exch_id(nid, _AGG_CHILD)] = xovf
        self._dist[id(op)] = SHARDED
        return out, ovf

    # ------------------------------------------------------ compilation
    def compile(self, plan, params):
        self.compiles += 1
        nodes = _number_nodes(plan)
        id_of = {id(o): i for i, o in nodes.items()}
        needed = self._needed_columns(plan)
        scans = self._collect_scans(plan)
        input_spec = []
        side: dict[str, tuple[Schema, dict]] = {}
        for s in scans:
            cols = needed.get(s.alias, set())
            if not cols:
                cols = {self.catalog[s.table].schema.fields[0].name}
            cols = tuple(sorted(cols))
            input_spec.append((s.alias, s.table, cols))
            t = self.catalog[s.table]
            sub_schema = Schema(
                tuple(f for f in t.schema.fields if f.name in cols))
            side[s.alias] = (
                sub_schema,
                {c: d for c, d in t.dicts.items() if c in cols},
            )

        from ..engine.executor import PACK_GUARD_BASE

        overflow_nodes = sorted(
            set(params.groupby_size) | set(params.join_cap)
            | set(params.exchange_cap)
            | {
                PACK_GUARD_BASE + nid
                for nid in params.pack_guard
                if nid not in params.groupby_nopack
            }
        )

        def emit(op, inputs):
            return self._emit_node(op, inputs, emit, params, id_of)

        from ..engine.executor import _collect_qparam_spec, _unpack_qparams

        qparam_spec = _collect_qparam_spec(plan)
        # the mesh-plan recorder for THIS compile. jit traces lazily, so
        # run_local binds it (and resets it — a retrace replays every
        # note) at trace entry; prepare() attaches the same object to the
        # prepared plan so the layout is visible once the program has run
        lowering = SpmdLowering(self.mesh_sig, self.nsh)
        self._lowering = lowering

        def run_local(raw_inputs, qparams):
            from ..expr import compile as expr_compile

            # trace-entry binding: emission-site notes (and the legacy
            # exchange log execute() reads) land in this compile's
            # recorder regardless of which plan this executor traced last
            self._lowering = lowering
            self._exch_log = lowering.legacy_log
            lowering.reset()
            # packed-vector ABI parity with the single-chip PreparedPlan
            # (a packed array here would otherwise hit bool(tracer))
            qparams = _unpack_qparams(qparams, qparam_spec)
            inputs = {}
            for alias, raw in raw_inputs.items():
                schema, dicts = side[alias]
                sel = raw["sel"]
                inputs[alias] = ColumnBatch(
                    cols=dict(raw["cols"]),
                    valid=dict(raw["valid"]),
                    sel=sel,
                    nrows=jnp.sum(sel, dtype=jnp.int64),
                    schema=schema,
                    dicts=dicts,
                )
            self._dist = {}
            prev = expr_compile.set_params(qparams if qparams else None)
            try:
                out, ovf = emit(plan, inputs)
            finally:
                expr_compile.set_params(prev)
            # compact BEFORE the root gather: the collective then moves
            # O(result) rows per shard instead of the full capacity
            from ..engine.executor import ROOT_COMPACT, compact_batch

            out, oc = compact_batch(out, params.join_cap[ROOT_COMPACT])
            ovf = dict(ovf)
            ovf[ROOT_COMPACT] = oc
            if self._dist[id(plan)] == SHARDED:
                out = self._gather_batch(out)
            # overflow counters must leave the shard_map replicated; psum
            # may multiply already-replicated counters by nsh, which is
            # harmless (the driver only tests >0)
            ovf_vec = jnp.stack([
                lax.psum(
                    ovf.get(n, jnp.zeros((), jnp.int64)), SHARD_AXIS
                )
                for n in overflow_nodes
            ]) if overflow_nodes else jnp.zeros((0,), jnp.int64)
            return out, ovf_vec

        def run(raw_inputs, qparams):
            in_specs = (
                jax.tree.map(lambda _: P(SHARD_AXIS), raw_inputs),
                jax.tree.map(lambda _: P(), qparams),
            )
            # no replication check: replication of the outputs
            # (all_gathered or psum-merged) is guaranteed by construction
            # but not statically inferable through gather-then-local-
            # compute chains; the PX test suite verifies it against
            # single-chip results
            return shard_map_compat(
                run_local,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=P(),
                check_replication=False,
            )(raw_inputs, qparams)

        return jax.jit(run), input_spec, overflow_nodes


class _PxChunkSourceExecutor(ChunkWindowMixin, PxExecutor):
    """PxExecutor whose streamed table reads one fixed-capacity chunk —
    every chunk of the out-of-core loop is one shard_map dispatch over
    the mesh (engine/chunked.py drives it exactly like the single-chip
    chunk executor; the slice/estimate logic lives in ChunkWindowMixin)."""

    chunking_enabled = False
    # legacy host-slice chunk loop: PX uploads must shard over the mesh
    # (jax.device_put of a staged pytree would land whole on one device),
    # so the streaming prefetch/decode pipeline stays single-chip
    supports_staged = False

    def __init__(self, catalog, stream_table: str, chunk_rows: int,
                 mesh=None, **kw):
        super().__init__(catalog, mesh, **kw)
        self.stream_table = stream_table
        self.chunk_rows = chunk_rows
        self._chunk: tuple[int, int] | None = None

    def table_batch(self, name: str, cols: tuple[str, ...]):
        if name != self.stream_table or self._chunk is None:
            return super().table_batch(name, cols)
        b = self._chunk_slice_batch(name, cols)
        # THE host-mediated DTL hop: each chunk of the streamed table
        # crosses host->device per dispatch. Counted so the mesh smoke can
        # assert the resident SPMD hot loop performs ZERO of these —
        # collectives move all steady-state data.
        m = self.metrics
        if m is not None:
            m.add("px dtl host hops")
        low = self._lowering
        if low is not None:
            low.note_host_hop()
        raw, _nbytes = shard_put(self.mesh, b)
        return raw


def _override(emit, node, result):
    """An emit view that returns a precomputed (exchanged) batch for one
    child node and delegates everything else."""

    def emit2(op, inputs):
        if op is node:
            return result
        return emit(op, inputs)

    return emit2
