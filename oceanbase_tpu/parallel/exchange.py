"""Exchange (repartition) primitives: PX/DTL lowered to XLA collectives.

Reference surface: the PX exchange operators + DTL channels —
ObPxTransmitOp/do_hash_dist routes each row to a target channel via
ObSliceIdxCalc (sql/engine/px/exchange/ob_px_dist_transmit_op.cpp:283,
ob_slice_calc.h:55), buffers serialize per-channel (dtl, credit flow
control), receivers drain a channel loop. The TPU redesign compiles the
whole exchange INTO the SPMD program:

- HASH          -> bucketize rows by key hash, `lax.all_to_all` over the
                   shard axis (this module's repartition_hash)
- BROADCAST     -> `lax.all_gather` (broadcast_rows)
- PARTITION(PKEY)-> repartition_hash with dest = owning shard of the
                   partition id (affine routing, same kernel)
- RANDOM        -> repartition with dest = round-robin counter
- RANGE         -> dest = searchsorted(range_bounds, key) (range_partition)
- aggregates    -> partial-agg + `psum` (merge_partials), the datahub
                   rollup analog

Flow control/credits disappear: the collective IS the synchronization.
Capacity discipline replaces dynamic buffers: each (src shard -> dst shard)
lane carries a static `cap` rows; overflow is counted and returned so the
engine can re-execute with a larger capacity (same pattern as joins).

All functions run INSIDE shard_map over mesh axis "shard" — which is why
there is no metrics recording here: Python side effects don't survive
tracing. DTL accounting (per-exchange lane capacity, shuffle rows/bytes,
worker spans) happens host-side at the px.py emission sites
(PxExecutor._note_exchange), once per compile, where capacities and
column counts are still static Python ints.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.hashing import hash32_combine
from .mesh import SHARD_AXIS


def dest_by_hash(key_cols: list[jnp.ndarray], n_shards: int) -> jnp.ndarray:
    """HASH distribution: shard id per row from mixed key hash (32-bit mix;
    TPUs emulate 64-bit integer multiplies)."""
    h = hash32_combine(key_cols)
    return (h % jnp.uint32(n_shards)).astype(jnp.int32)


def dest_by_range(
    key: jnp.ndarray, bounds: jnp.ndarray
) -> jnp.ndarray:
    """RANGE distribution: bounds are n_shards-1 ascending split points."""
    return jnp.searchsorted(bounds, key, side="right").astype(jnp.int32)


def dest_round_robin(mask: jnp.ndarray, n_shards: int, shard_id) -> jnp.ndarray:
    """RANDOM(_LOCAL) distribution: even resplit of live rows."""
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    return ((pos + shard_id) % n_shards).astype(jnp.int32)


def repartition(
    cols: dict[str, jnp.ndarray],
    mask: jnp.ndarray,
    dest: jnp.ndarray,
    n_shards: int,
    cap: int,
    axis_name: str = SHARD_AXIS,
):
    """Redistribute rows to their dest shard via all_to_all.

    Returns (new_cols, new_mask [n_shards*cap], overflow: scalar count of
    rows dropped because a (src,dst) lane exceeded cap). Call inside
    shard_map. cap is per source->dest lane.

    Lane packing is SORT-based (sort rows by dest, lanes are contiguous
    windows of the sorted order read back by gather) — a TPU scatter costs
    ~1.1s per 8M rows, a sort ~20ms.
    """
    n = mask.shape[0]
    dest = jnp.where(mask, dest, n_shards)  # dead rows -> dropped
    idx = jnp.arange(n, dtype=jnp.int32)
    sd, sidx = lax.sort((dest, idx), num_keys=1)
    counts = jnp.stack([
        jnp.sum(sd == d, dtype=jnp.int64) for d in range(n_shards)
    ])
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int64), jnp.cumsum(counts)[:-1]]
    )
    overflow = jnp.sum(jnp.maximum(counts - cap, 0))
    s = jnp.arange(cap, dtype=jnp.int64)
    pos = offs[:, None] + s[None, :]  # (n_shards, cap) sorted positions
    sent_mask = s[None, :] < jnp.minimum(counts, cap)[:, None]
    take = sidx[jnp.clip(pos, 0, n - 1).reshape(-1)]
    send = {
        name: c[take].reshape(n_shards, cap) for name, c in cols.items()
    }

    recv = {}
    for name, buf in send.items():
        recv[name] = lax.all_to_all(
            buf, axis_name, split_axis=0, concat_axis=0, tiled=True
        ).reshape(n_shards * cap)
    new_mask = lax.all_to_all(
        sent_mask, axis_name, split_axis=0, concat_axis=0, tiled=True
    ).reshape(n_shards * cap)
    overflow = lax.psum(overflow, axis_name)
    return recv, new_mask, overflow


def broadcast_rows(
    cols: dict[str, jnp.ndarray],
    mask: jnp.ndarray,
    axis_name: str = SHARD_AXIS,
):
    """BROADCAST distribution: every shard receives all rows (all_gather)."""
    out = {
        name: lax.all_gather(c, axis_name, tiled=True) for name, c in cols.items()
    }
    new_mask = lax.all_gather(mask, axis_name, tiled=True)
    return out, new_mask


def ring_broadcast_rows(
    cols: dict[str, jnp.ndarray],
    mask: jnp.ndarray,
    n_shards: int,
    axis_name: str = SHARD_AXIS,
):
    """BROADCAST distribution on a ring schedule: n_shards-1 ppermute
    steps, each shard forwarding the block it just received to its
    neighbor.

    Bit-identical output layout to broadcast_rows (all_gather
    tiled=True): shard i's rows land at offset i*n on every shard. The
    point of the variant: all_gather's bisection schedule peaks at
    log2(n) concurrent link pairs, while the ring moves one block per
    ICI hop per step — on torus topologies with a congested axis the
    ring keeps per-link pressure flat (the classic bandwidth-optimal
    ring collective). Selected via PxExecutor(broadcast_impl="ring");
    the lowering records "ppermute" as the collective so the plan
    monitor distinguishes the schedules."""
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    me = lax.axis_index(axis_name)

    def gather_one(x):
        n = x.shape[0]
        out = jnp.zeros((n_shards * n,) + x.shape[1:], x.dtype)
        out = lax.dynamic_update_slice_in_dim(out, x, me * n, axis=0)
        blk = x
        for s in range(1, n_shards):
            blk = lax.ppermute(blk, axis_name, perm)
            # after s forwards, the block in hand originated at shard
            # (me - s) mod n_shards; place it at that shard's offset
            out = lax.dynamic_update_slice_in_dim(
                out, blk, ((me - s) % n_shards) * n, axis=0
            )
        return out

    return {name: gather_one(c) for name, c in cols.items()}, gather_one(mask)


def merge_partials(partials, axis_name: str = SHARD_AXIS):
    """Merge per-shard partial aggregates (datahub rollup analog)."""
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), partials)


def sample_range_bounds(
    key: jnp.ndarray,
    mask: jnp.ndarray,
    n_shards: int,
    axis_name: str = SHARD_AXIS,
    resolution: int = 4096,
) -> jnp.ndarray:
    """RANGE distribution support: n_shards-1 ascending split points chosen
    so each range holds ~equal global row counts.

    The reference samples rows through the datahub (dynamic-sample message,
    px/datahub/components) to pick range boundaries for range-dist sort and
    window exchanges; the SPMD analog builds a global psum histogram over
    the key span — every shard derives identical bounds with no host round
    trip. Integer keys only (dict codes, dates, ints)."""
    k64 = key.astype(jnp.int64)
    big = jnp.int64(jnp.iinfo(jnp.int64).max)
    kmin = lax.pmin(jnp.min(jnp.where(mask, k64, big)), axis_name)
    kmax = lax.pmax(jnp.max(jnp.where(mask, k64, -big - 1)), axis_name)
    span = jnp.maximum(kmax - kmin + 1, 1)
    # equal-width buckets of integer step: (k-kmin)//step never overflows,
    # unlike (k-kmin)*resolution which wraps for spans beyond ~2^51
    step = jnp.maximum((span + resolution - 1) // resolution, 1)
    bucket = jnp.clip((k64 - kmin) // step, 0, resolution - 1).astype(jnp.int32)
    hist = jnp.zeros(resolution, dtype=jnp.int64).at[
        jnp.where(mask, bucket, resolution)
    ].add(1, mode="drop")
    hist = lax.psum(hist, axis_name)
    cdf = jnp.cumsum(hist)
    total = cdf[-1]
    # bound i = smallest bucket whose cdf covers quantile (i+1)/n_shards
    targets = (jnp.arange(1, n_shards, dtype=jnp.int64) * total) // n_shards
    idx = jnp.searchsorted(cdf, targets, side="left")
    # exclusive key-space upper bound of each chosen bucket (pairs with
    # dest_by_range's side="right"); (idx+1)*step <= span+resolution, no
    # overflow
    return kmin + (idx + 1) * step


def bc2host(
    cols: dict[str, jnp.ndarray],
    mask: jnp.ndarray,
    per_host: int,
    axis_name: str = SHARD_AXIS,
):
    """BC2HOST (SM_BROADCAST): one copy of every row per HOST, split across
    that host's workers.

    Mesh layout contract: consecutive runs of `per_host` shards form one
    host (the natural ICI-within-DCN-across layout). Implemented as a full
    all_gather followed by a lane filter — each host collectively holds all
    rows exactly once, striped over its workers. On a 2-level topology XLA
    lowers the gather hierarchically, which is the reference's intent
    (broadcast per host, random within host)."""
    out, m = broadcast_rows(cols, mask, axis_name)
    lane = lax.axis_index(axis_name) % per_host
    stripe = jnp.arange(m.shape[0], dtype=jnp.int32) % per_host
    return out, m & (stripe == lane)


def dest_by_partition(
    part_ids: jnp.ndarray, owner_of_partition: jnp.ndarray
) -> jnp.ndarray:
    """PARTITION (PKEY) distribution: route each row to the shard owning
    its partition (partial partition-wise join / PKEY DML). The owner map
    is the location-cache's tablet->shard assignment shipped to device."""
    return owner_of_partition[part_ids].astype(jnp.int32)
