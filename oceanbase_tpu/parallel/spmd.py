"""Mesh-SPMD execution subsystem: the explicit plan-level representation
of distributed execution over a device mesh.

Reference surface: the PX plan tree — ObPxTransmit/ObPxReceive pairs
mark DFO boundaries, each annotated with a distribution method
(ob_sql_define.h ObPQDistributeMethod) and wired through DTL channels at
runtime. The TPU rebuild compiles every exchange INTO one shard_map
program (parallel/px.py), so the channel graph disappears from runtime —
but the *representation* must not: operators, observability and the
artifact store all need a first-class answer to "what collectives does
this plan dispatch, over which mesh, moving how many bytes".

This module is that answer:

  * ``mesh_signature``  — the restart-stable identity of a mesh (axis
    shape + axis names). Joins the plan-artifact key so an SPMD program
    exported on one mesh shape can never hydrate onto another.
  * ``MeshExchange`` / ``MeshPlan`` — the mesh-aware physical-plan
    layer: one record per exchange boundary the lowering emitted, each
    naming its PX kind (broadcast / repartition / merge / ...) and the
    XLA collective it lowered to (all_gather / all_to_all / psum /
    ppermute), with static lane capacities -> per-dispatch byte volume.
  * ``SpmdLowering`` — the per-compile recorder px.py's emission sites
    write through at trace time. jax.jit traces lazily, so the recorder
    object rides the compiled program's closure and the SAME MeshPlan
    instance attached to the PreparedPlan fills in on first dispatch
    (and resets cleanly if jit ever retraces).
  * ``ShardedResidency`` — the partitioned residency ledger for the
    executor's upload path: a table uploaded as sharded device arrays
    holds bytes/n_shards per device, which is what the memory governor
    must charge (engine/memory_governor.register_sharded_residency).
  * ``shard_put`` — partition a host-built ColumnBatch across the mesh
    as row-sharded device arrays (the granule map made physical).

Single-chip is the degenerate 1-device mesh: every structure here is
exercised on CPU under ``--xla_force_host_platform_device_count=8``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import SHARD_AXIS, mesh_signature

#: PX exchange kind -> the XLA collective it lowers to by default.
#: "broadcast" may lower to "ppermute" instead when the executor's
#: broadcast_impl knob selects the ring schedule (exchange.py
#: ring_broadcast_rows) — the lowering records the ACTUAL collective.
KIND_COLLECTIVE = {
    "broadcast": "all_gather",
    "repartition": "all_to_all",
    "merge": "psum",
    "bloom": "psum",
    "skew_histogram": "psum",
    "range_sample": "psum",
}


@dataclass(frozen=True)
class MeshExchange:
    """One exchange boundary of a compiled SPMD program (the
    ObPxTransmit/Receive pair analog), fully static: capacities and
    column counts are Python ints at trace time."""

    kind: str  # PX distribution kind (broadcast/repartition/merge/...)
    collective: str  # XLA collective it lowered to
    ncols: int  # payload columns (cols + validity lanes)
    lane_cap: int  # rows per lane
    lanes: int  # lane count across the mesh
    nbytes: int  # per-dispatch byte capacity the collective moves

    def describe(self) -> str:
        return (f"{self.kind}->{self.collective}"
                f"[{self.ncols}x{self.lane_cap}x{self.lanes}]")


@dataclass
class MeshPlan:
    """Mesh-aware physical plan summary: which collectives one jitted
    SPMD program dispatches, over which mesh. Attached to the
    PreparedPlan (and pickled into the plan artifact) so cached and
    warm-booted plans keep their exchange layout."""

    mesh_sig: tuple  # ((shape...), (axis names...))
    n_shards: int
    exchanges: list = field(default_factory=list)
    # host-mediated data hops the compiled HOT LOOP performs per
    # dispatch. Zero for resident SPMD plans — the acceptance invariant
    # tools/mesh_smoke.py pins; chunk-streamed plans count one per
    # chunk upload (the data genuinely crosses the host each dispatch).
    host_hops: int = 0

    @property
    def total_ops(self) -> int:
        return len(self.exchanges)

    @property
    def total_bytes(self) -> int:
        return sum(e.nbytes for e in self.exchanges)

    def ops_by_collective(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.exchanges:
            out[e.collective] = out.get(e.collective, 0) + 1
        return out

    def bytes_by_collective(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.exchanges:
            out[e.collective] = out.get(e.collective, 0) + e.nbytes
        return out

    def describe(self) -> str:
        """Compact per-collective summary for the plan monitor row."""
        return ",".join(
            f"{c}:{n}" for c, n in sorted(self.ops_by_collective().items())
        )


class SpmdLowering:
    """Per-compile exchange recorder.

    px.py creates one per compile() and binds it at the top of the
    traced program body; every emission-site note lands here. Because
    jit traces lazily, the MeshPlan it owns is attached to the
    PreparedPlan BEFORE the first dispatch and fills in during it —
    reset() at trace entry keeps a retrace from double-counting.
    """

    def __init__(self, mesh_sig: tuple, n_shards: int):
        self.plan = MeshPlan(mesh_sig=mesh_sig, n_shards=n_shards)
        # legacy (kind, ncols, cap) triples: the worker-span and
        # peak-bytes consumers predate MeshExchange and read this shape
        self.legacy_log: list[tuple[str, int, int]] = []

    def reset(self) -> None:
        """Called at trace entry: a jit retrace replays every emission
        note, so the recorder must start from zero each trace."""
        self.plan.exchanges.clear()
        self.plan.host_hops = 0
        del self.legacy_log[:]

    def note(self, kind: str, ncols: int, cap: int, lanes: int,
             collective: str | None = None, elem_bytes: int = 8,
             legacy: bool = True) -> None:
        if collective is None:
            collective = KIND_COLLECTIVE.get(kind, kind)
        self.plan.exchanges.append(MeshExchange(
            kind=kind, collective=collective, ncols=ncols, lane_cap=cap,
            lanes=lanes, nbytes=ncols * cap * lanes * elem_bytes,
        ))
        # reductions (legacy=False) stay out of the (kind, ncols, cap)
        # triple log: its consumers size row-exchange worker spans and
        # peak shuffle bytes, where a psum of group partials is noise
        if legacy:
            self.legacy_log.append((kind, ncols, cap))

    def note_host_hop(self) -> None:
        self.plan.host_hops += 1


class ShardedResidency:
    """Partitioned residency ledger: which base tables are resident as
    sharded device arrays, and how many bytes each device actually
    holds (total/n_shards — row sharding splits every column evenly).

    The memory governor charges ``per_device_bytes()`` against its
    per-device HBM budget (register_sharded_residency); virtual tables
    and the mesh smoke read ``tables()``. Thread-safe: uploads happen
    under serving concurrency."""

    def __init__(self, n_shards: int):
        self.n_shards = max(1, int(n_shards))
        self._tables: dict[str, int] = {}
        self._lock = threading.Lock()

    def charge(self, table: str, nbytes: int) -> None:
        with self._lock:
            self._tables[table] = self._tables.get(table, 0) + int(nbytes)

    def discharge(self, table: str) -> None:
        with self._lock:
            self._tables.pop(table, None)

    def clear(self) -> None:
        with self._lock:
            self._tables.clear()

    def total_bytes(self) -> int:
        with self._lock:
            return sum(self._tables.values())

    def per_device_bytes(self) -> int:
        """What ONE device of the mesh holds — the governor's unit of
        account (its budget is per-device HBM)."""
        return self.total_bytes() // self.n_shards

    def tables(self) -> dict[str, int]:
        with self._lock:
            return dict(self._tables)


def shard_put(mesh, batch):
    """Partition a host-built ColumnBatch across the mesh as row-sharded
    device arrays (jax.device_put with a NamedSharding over the shard
    axis — the granule map made physical). Returns (raw, nbytes): the
    raw {"cols", "valid", "sel"} dict the SPMD program takes as one
    input leaf group, and the TOTAL bytes placed (bytes/n_shards of it
    lands per device)."""
    shard = NamedSharding(mesh, P(SHARD_AXIS))
    raw = {
        "cols": {n: jax.device_put(a, shard) for n, a in batch.cols.items()},
        "valid": {n: jax.device_put(a, shard)
                  for n, a in batch.valid.items()},
        "sel": jax.device_put(batch.sel, shard),
    }
    nbytes = sum(
        int(a.nbytes)
        for d in (raw["cols"], raw["valid"])
        for a in d.values()
    ) + int(raw["sel"].nbytes)
    return raw, nbytes


__all__ = [
    "KIND_COLLECTIVE",
    "MeshExchange",
    "MeshPlan",
    "ShardedResidency",
    "SpmdLowering",
    "mesh_signature",
    "shard_put",
]
