"""Device mesh management.

Reference surface: PX worker/SQC topology — a query runs at DOP d across
nodes, each node hosting worker threads (sql/engine/px/ob_px_sub_coord.cpp,
ob_px_worker.h:229). The TPU mapping: one mesh axis "shard" enumerates the
execution shards (device = worker); multi-host slices extend the same mesh
over ICI/DCN and XLA routes the collectives (SURVEY.md §2.7). A second
optional axis "host" models the 2-level PARTITION_HASH/BC2HOST slave-mapping
methods (hierarchical exchanges).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"

# jax >= 0.6 exposes shard_map at top level with a check_vma kwarg; older
# releases keep it in jax.experimental with the check_rep spelling. The
# replication-check intent ("statically verify output replication") is
# the same — only the location and keyword differ.
if hasattr(jax, "shard_map"):
    _shard_map, _SM_CHECK_KW = jax.shard_map, "check_vma"
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _SM_CHECK_KW = "check_rep"


def shard_map_compat(f, *, mesh, in_specs, out_specs,
                     check_replication=True):
    """Version-portable shard_map: every SPMD program in the engine (and
    its tests) routes through here instead of spelling the jax API."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_SM_CHECK_KW: check_replication})


# Multi-process runtimes (the DCN half of SURVEY §2.7's architectural
# translation: ICI within a slice = one process's devices, DCN across
# slices = jax.distributed's cross-process collectives — gloo on CPU,
# real DCN transport on TPU pods): call jax.distributed.initialize
# BEFORE importing anything from this package (package imports build jnp
# constants, which locks the backend) — after that jax.devices() is the
# GLOBAL list and the same shard_map PX programs run SPMD across
# processes, exactly like the reference's SQC dispatch spans observers
# (sql/engine/px/ob_px_rpc_processor.h:28). See
# tests/test_px_multiproc.py and __graft_entry__._mp_px_worker.


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"mesh needs {n_devices} devices but only {len(devices)} "
                "are available; silently shrinking would break exchange "
                "capacity math"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows split across shards (granule assignment, static)."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
