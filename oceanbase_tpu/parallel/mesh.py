"""Device mesh management.

Reference surface: PX worker/SQC topology — a query runs at DOP d across
nodes, each node hosting worker threads (sql/engine/px/ob_px_sub_coord.cpp,
ob_px_worker.h:229). The TPU mapping: one mesh axis "shard" enumerates the
execution shards (device = worker); multi-host slices extend the same mesh
over ICI/DCN and XLA routes the collectives (SURVEY.md §2.7). A second
optional axis "host" models the 2-level PARTITION_HASH/BC2HOST slave-mapping
methods (hierarchical exchanges).
"""

from __future__ import annotations

import inspect

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


def _resolve_shard_map():
    """Locate shard_map and its replication-check keyword for the
    installed jax.

    jax >= 0.6 exposes shard_map at top level with a check_vma kwarg;
    0.4.x keeps it in jax.experimental with the check_rep spelling. The
    intent ("statically verify output replication") is the same — only
    location and keyword differ. Rather than guessing the kwarg from the
    location (which silently rotted once: top-level shard_map briefly
    shipped while still spelling check_rep), inspect the actual
    signature and pick whichever spelling it accepts; if a future
    release drops both, degrade to not forwarding the flag at all.
    tests/test_mesh_spmd.py pins this resolution against the pinned jax
    so drift surfaces as a test failure, not a TypeError at query time.
    """
    if hasattr(jax, "shard_map"):
        fn = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as fn
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # pragma: no cover - C-level callable
        params = {}
    kw = next((k for k in ("check_vma", "check_rep") if k in params), None)
    return fn, kw


_shard_map, _SM_CHECK_KW = _resolve_shard_map()


def shard_map_compat(f, *, mesh, in_specs, out_specs,
                     check_replication=True):
    """Version-portable shard_map: every SPMD program in the engine (and
    its tests) routes through here instead of spelling the jax API."""
    check = {} if _SM_CHECK_KW is None else {_SM_CHECK_KW: check_replication}
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **check)


def mesh_signature(mesh: Mesh) -> tuple:
    """Restart-stable identity of a mesh: axis sizes + axis names.

    Device ids deliberately excluded — a warm boot enumerates devices in
    the same order but with fresh client handles; what an exported SPMD
    program actually depends on is the axis geometry its shardings were
    lowered against. Joins the plan-artifact key (engine/plan_artifact)
    and the hydrate-time guard so a program exported on one mesh shape
    can never run with another's shardings."""
    return (
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        tuple(str(a) for a in mesh.axis_names),
    )


# Multi-process runtimes (the DCN half of SURVEY §2.7's architectural
# translation: ICI within a slice = one process's devices, DCN across
# slices = jax.distributed's cross-process collectives — gloo on CPU,
# real DCN transport on TPU pods): call jax.distributed.initialize
# BEFORE importing anything from this package (package imports build jnp
# constants, which locks the backend) — after that jax.devices() is the
# GLOBAL list and the same shard_map PX programs run SPMD across
# processes, exactly like the reference's SQC dispatch spans observers
# (sql/engine/px/ob_px_rpc_processor.h:28). See
# tests/test_px_multiproc.py and __graft_entry__._mp_px_worker.


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"mesh needs {n_devices} devices but only {len(devices)} "
                "are available; silently shrinking would break exchange "
                "capacity math"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows split across shards (granule assignment, static)."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
