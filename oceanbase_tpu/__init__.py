"""oceanbase_tpu — a TPU-native distributed SQL (HTAP) engine.

A ground-up rebuild of the capabilities of OceanBase (reference: /root/reference)
designed TPU-first:

- column batches are SoA JAX device arrays (reference: expression frames,
  src/sql/engine/expr/ob_expr.h:541 and rich vector formats,
  src/share/vector/type_traits.h:23),
- the vectorized operator hot loops (scan/filter/project, hash join, hash
  group-by, sort — reference: src/sql/engine/ob_operator.cpp:1425) are
  `jax.jit` programs,
- the PX parallel-exchange layer (reference: src/sql/engine/px +
  src/sql/dtl) lowers to XLA collectives over a `jax.sharding.Mesh`,
- the SQL compiler, MVCC transactions, LSM storage and Paxos-replicated log
  remain host-side components.

64-bit integer support is required for SQL semantics (BIGINT, scaled-decimal
arithmetic), so x64 mode is enabled at import. All kernels are explicit about
dtypes; nothing relies on JAX's default widths.
"""

from jax import config as _jax_config

_jax_config.update("jax_enable_x64", True)

__version__ = "0.1.0"
