"""Log stream (LS): the replication unit binding tablets to a replicated log.

Reference surface: storage/ls + tx_storage — an LS is the unit of Paxos
replication; it hosts tablets, a palf log, an apply service (leader) and a
replay service (followers): committed tx log entries drive memtable state
(ObLSTabletService, apply/replay services logservice/applyservice,
replayservice; ObTxReplayExecutor storage/tx/ob_tx_replay_executor.cpp:28).

The rebuild's LSReplica owns {palf replica, tablets, tx table} for one
replica of one LS. All replicas apply the same committed records in LSN
order; the difference between leader "apply" and follower "replay" is only
whether the mutations were already staged locally by an executing tx:

  * leader: tx staged rows at execution time -> apply resolves them
    (memtable.commit / abort);
  * follower (or a restarted leader): nothing staged -> replay inserts the
    committed versions directly.

Commit acknowledgement: on_tx_applied callbacks fire when a tx's decisive
record (REDO_COMMIT / COMMIT / ABORT) is applied on this replica — the
TransService uses the leader's callback to release the waiting session
(the ObEndTransCallback analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..core.dtypes import Schema
from ..log import LocalBus, LogEntry, PalfReplica, Role
from ..storage import Tablet
from .records import Mutation, RecordType, TxRecord


@dataclass
class LSReplica:
    ls_id: int
    node_id: int
    palf: PalfReplica
    tablets: dict[int, Tablet] = field(default_factory=dict)
    # tx table: uncommitted tx state on this replica (ObTxTable analog)
    tx_table: dict[int, str] = field(default_factory=dict)  # tx_id -> state
    # txs whose mutations this replica staged at execution time (leader path)
    _locally_staged: set[int] = field(default_factory=set)
    # follower-side redo retained from PREPARE until COMMIT/ABORT
    _pending_redo: dict[int, tuple[Mutation, ...]] = field(default_factory=dict)
    on_tx_applied: Callable[[int, RecordType, int], None] | None = None
    # observer of every applied record (the multi-data-source consumer
    # analog): the server layer uses it to re-apply logged dictionary
    # appends and advance GTS during boot-time replay
    on_record: Callable[[TxRecord], None] | None = None

    def __post_init__(self):
        self.palf.on_commit = self._apply

    # ----------------------------------------------------------- tablets
    def create_tablet(self, tablet_id: int, schema: Schema, key_cols: list[str]) -> Tablet:
        t = Tablet(tablet_id, schema, key_cols)
        self.tablets[tablet_id] = t
        return t

    @property
    def is_leader(self) -> bool:
        return self.palf.role is Role.LEADER

    @property
    def is_ready(self) -> bool:
        """Leader with all committed entries applied — safe to serve."""
        return self.palf.is_ready_leader

    # ------------------------------------------------------ execution path
    def stage_locally(self, tx_id: int, read_snapshot: int, m: Mutation) -> None:
        """Leader-side execution: stage into the tablet memtable now; the
        redo reaches the log only at commit time."""
        self.tablets[m.tablet_id].stage(tx_id, read_snapshot, m.key, m.op, m.values)
        self._locally_staged.add(tx_id)
        self.tx_table[tx_id] = "active"

    def abort_locally(self, tx_id: int) -> None:
        for t in self.tablets.values():
            t.abort_tx(tx_id)
        self._locally_staged.discard(tx_id)
        self.tx_table.pop(tx_id, None)

    def submit_record(self, rec: TxRecord) -> int | None:
        # scn latches to max(prev+1, commit_version): with submits
        # serialized under GtsService.submit_lock, a replica's applied scn
        # then dominates every applied commit version — the follower-read
        # watermark (see apply_watermark)
        return self.palf.submit_log(rec.to_bytes(), scn=rec.commit_version)

    @property
    def apply_watermark(self) -> int:
        """Every tx with commit_version <= this has applied on THIS
        replica; a snapshot read at any ts <= watermark is complete."""
        return self.palf.applied_scn

    # ------------------------------------------------------- apply/replay
    def _apply(self, entry: LogEntry) -> None:
        if not entry.payload:
            return  # leadership no-op entry
        rec = TxRecord.from_bytes(entry.payload)
        if self.on_record is not None:
            self.on_record(rec)
        staged = rec.tx_id in self._locally_staged
        if rec.rtype is RecordType.REDO_COMMIT:
            if staged:
                for t in self.tablets.values():
                    t.commit_tx(rec.tx_id, rec.commit_version)
                self._locally_staged.discard(rec.tx_id)
            else:
                self._replay_mutations(rec.mutations, rec.commit_version)
            self.tx_table.pop(rec.tx_id, None)
            self._notify(rec.tx_id, rec.rtype, rec.commit_version)
        elif rec.rtype in (RecordType.PREPARE, RecordType.XA_PREPARE):
            if not staged:
                # follower: remember redo; rows become visible at COMMIT with
                # the final version (staging uncommitted rows would need
                # speculative nodes — simpler and equivalent to defer)
                self.tx_table[rec.tx_id] = "prepared"
                self._pending_redo[rec.tx_id] = rec.mutations
            else:
                self.tx_table[rec.tx_id] = "prepared"
            self._notify(rec.tx_id, rec.rtype, 0)
        elif rec.rtype is RecordType.COMMIT:
            if staged:
                for t in self.tablets.values():
                    t.commit_tx(rec.tx_id, rec.commit_version)
                self._locally_staged.discard(rec.tx_id)
            else:
                self._replay_mutations(
                    self._pending_redo.pop(rec.tx_id, ()), rec.commit_version
                )
            self.tx_table.pop(rec.tx_id, None)
            self._notify(rec.tx_id, rec.rtype, rec.commit_version)
        elif rec.rtype is RecordType.ABORT:
            if staged:
                self.abort_locally(rec.tx_id)
            self._pending_redo.pop(rec.tx_id, None)
            self.tx_table.pop(rec.tx_id, None)
            self._notify(rec.tx_id, rec.rtype, 0)

    def _replay_mutations(self, mutations, version: int) -> None:
        for m in mutations:
            t = self.tablets.get(m.tablet_id)
            if t is not None:
                t.active.replay(m.key, m.op, m.values, version)

    def _notify(self, tx_id: int, rtype: RecordType, version: int) -> None:
        if self.on_tx_applied is not None:
            self.on_tx_applied(tx_id, rtype, version)


def make_ls_group(
    ls_id: int,
    node_ids: list[int],
    bus: LocalBus,
    palf_id_base: int = 0,
    data_dir: str | None = None,
    fsync: bool = True,
) -> dict[int, LSReplica]:
    """Create one LS's replicas across nodes sharing a bus.

    Bus addresses must be unique per (ls, node): address = base + node_id.
    With data_dir, each replica gets a durable LogStore at
    `{data_dir}/n{node}/ls_{ls}` and reloads any existing log + election
    meta from it (restart recovery).
    """
    addrs = [palf_id_base + n for n in node_ids]
    out = {}
    for n in node_ids:
        store = None
        if data_dir is not None:
            import os

            from ..log.store import LogStore

            store = LogStore(
                os.path.join(data_dir, f"n{n}", f"ls_{ls_id}"), fsync=fsync
            )
        palf = PalfReplica(palf_id_base + n, addrs, bus, store=store)
        out[n] = LSReplica(ls_id, n, palf)
    return out
