"""Transaction log records (the redo/commit wire format).

Reference surface: storage/tx tx log types written through the log-cb
manager (ob_tx_log_cb_mgr.h) — redo (memtable mutators,
ob_memtable_mutator.h), prepare, commit, abort records — replayed on
followers by ObTxReplayExecutor (ob_tx_replay_executor.cpp:28).

Records serialize with a small tag + pickle body. Pickle is acceptable here
because log payloads are produced and consumed only by this process group
(never untrusted input); a fixed binary layout can replace it without
touching any call site (to_bytes/from_bytes is the only boundary).
"""

from __future__ import annotations

import enum
import pickle
from dataclasses import dataclass, field


class RecordType(enum.IntEnum):
    REDO_COMMIT = 1  # 1PC: mutations + commit version in one record
    PREPARE = 2  # 2PC phase 1: mutations, participant list
    COMMIT = 3  # 2PC phase 2: commit version
    ABORT = 4
    # XA phase 1: like PREPARE (redo + participants reach the log, replicas
    # retain pending redo) but the decision belongs to an EXTERNAL
    # coordinator — applying it must never auto-commit. The record also
    # carries the xid/owner/tenant so a restarted node can rebuild its
    # parked-branch registry from replay alone (the reference logs prepare
    # state through the part ctx, ob_trans_part_ctx.h:154).
    XA_PREPARE = 5


@dataclass(frozen=True)
class Mutation:
    tablet_id: int
    key: tuple
    op: int  # storage.OP_PUT / OP_DELETE
    values: tuple | None


@dataclass(frozen=True)
class TxRecord:
    rtype: RecordType
    tx_id: int
    mutations: tuple[Mutation, ...] = ()
    commit_version: int = 0
    coordinator_ls: int = 0
    participants: tuple[int, ...] = ()
    # dictionary growth caused by this tx: (tablet_id, column, code,
    # string). VARCHAR cells in mutations store dictionary CODES; logging
    # the appends makes the log self-describing for CDC and PITR restore
    # (the multi-data-source analog: non-row state atomically logged with
    # the tx, storage/multi_data_source).
    dict_appends: tuple = ()
    # XA_PREPARE only: external branch id + the preparing user + owning
    # tenant (records are observed by every tenant on a shared cluster;
    # tenant scopes the registry rebuild)
    xid: str = ""
    owner: str = ""
    tenant: str = ""

    def to_bytes(self) -> bytes:
        return bytes([self.rtype]) + pickle.dumps(
            (self.tx_id, self.mutations, self.commit_version,
             self.coordinator_ls, self.participants, self.dict_appends,
             self.xid, self.owner, self.tenant),
            protocol=pickle.HIGHEST_PROTOCOL,
        )

    @staticmethod
    def from_bytes(b: bytes) -> "TxRecord":
        rtype = RecordType(b[0])
        fields = pickle.loads(b[1:])
        return TxRecord(rtype, *fields)
