"""Transaction service: snapshot-isolation transactions over log streams.

Reference surface: storage/tx ObTransService (ob_trans_service.h:180) and
the participant ctx ObPartTransCtx (ob_trans_part_ctx.h:154): transactions
execute against leader memtables, redo reaches the replicated log at commit,
a single-LS tx commits in one log write (1PC, ob_trans_part_ctx.h:222), a
multi-LS tx runs two-phase commit among LS leaders
(ob_two_phase_committer.h) with the commit version from GTS.

Rebuild semantics (documented divergences):
  * snapshot isolation: read snapshot fixed at begin() from GTS; writes
    stage in leader memtables under tx_id; first-committer-wins on
    write-write conflicts (memtable raises WriteConflict);
  * 1PC: one REDO_COMMIT record carrying mutations + commit version;
  * 2PC: PREPARE records carry each participant's redo; after all prepares
    apply, the commit version is taken from the single per-tenant GTS and
    COMMIT records fan out (the reference derives it as max(prepare log
    scn); with one GTS authority a single fetch is equivalent);
  * commit acknowledgement = the decisive record APPLYING on the local
    replica (which implies it committed in the log).

The service is event-driven off the LS apply callbacks; `drive`-style
helpers (tx/cluster.py) pump the virtual clock in tests and single-process
deployments.
"""

from __future__ import annotations

import enum
import itertools
import threading
from dataclasses import dataclass, field

from ..storage import WriteConflict  # re-export convenience  # noqa: F401
from .gts import GtsService
from .ls import LSReplica
from .records import Mutation, RecordType, TxRecord


class TxState(enum.Enum):
    ACTIVE = "active"
    PREPARING = "preparing"
    # XA: prepared and PARKED — redo is durable in the log on every
    # participant, the decision belongs to an external coordinator
    XA_PREPARED = "xa_prepared"
    COMMITTING = "committing"
    COMMITTED = "committed"
    ABORTED = "aborted"


class NotMaster(Exception):
    """The local LS replica is not the leader; retry at the leader.

    Carries the offending ls_id so the statement retry layer can
    invalidate exactly that location-cache entry instead of dropping the
    whole cache (share/retry.py LOCATION_REFRESH handling)."""

    def __init__(self, msg: str = "", ls_id: int | None = None):
        super().__init__(msg)
        self.ls_id = ls_id


@dataclass
class TxContext:
    tx_id: int
    read_snapshot: int
    state: TxState = TxState.ACTIVE
    mutations: dict[int, list[Mutation]] = field(default_factory=dict)  # ls_id ->
    # dictionary appends to log with the commit (see TxRecord.dict_appends)
    dict_appends: list = field(default_factory=list)
    commit_version: int = 0
    # XA participant set: fixed at xa_prepare (includes the home LS when the
    # branch has no writes, so even an empty branch leaves a durable record)
    xa_parts: tuple = ()
    # the external decision once taken ("commit"/"rollback"): a retry after
    # a transient submit failure must re-drive the SAME decision, never
    # reverse one whose records may already be in a participant log
    xa_decision: str | None = None
    _prepared: set[int] = field(default_factory=set)
    _committed_ls: set[int] = field(default_factory=set)
    # COMMIT decisions whose submit was rejected (transient non-leader
    # window); resubmitted by retry_decisions
    _undelivered: dict[int, "TxRecord"] = field(default_factory=dict)

    @property
    def is_done(self) -> bool:
        return self.state in (TxState.COMMITTED, TxState.ABORTED)


@dataclass
class TransService:
    """Per-node transaction manager over that node's LS replicas."""

    node_id: int
    gts: GtsService
    replicas: dict[int, LSReplica]  # ls_id -> local replica
    _txs: dict[int, TxContext] = field(default_factory=dict)
    _lock: threading.RLock = field(default_factory=threading.RLock)
    _tx_ids: "itertools.count[int]" = None  # type: ignore[assignment]

    def __post_init__(self):
        if self._tx_ids is None:
            # tx ids globally unique across nodes: high bits = node
            self._tx_ids = itertools.count(self.node_id * 1_000_000_000 + 1)
        for r in self.replicas.values():
            prev = r.on_tx_applied
            r.on_tx_applied = self._make_applied_cb(r.ls_id, prev)

    def _make_applied_cb(self, ls_id: int, prev):
        def cb(tx_id: int, rtype: RecordType, version: int):
            if prev is not None:
                prev(tx_id, rtype, version)
            self._on_applied(ls_id, tx_id, rtype, version)

        return cb

    # ------------------------------------------------------------- API
    def begin(self) -> TxContext:
        ctx = TxContext(next(self._tx_ids), self.gts.current())
        with self._lock:
            self._txs[ctx.tx_id] = ctx
        return ctx

    def write(self, ctx: TxContext, ls_id: int, tablet_id: int, key: tuple,
              op: int, values: tuple | None) -> None:
        if ctx.state is not TxState.ACTIVE:
            raise RuntimeError(f"tx {ctx.tx_id} is {ctx.state.value}")
        r = self.replicas[ls_id]
        if not r.is_ready:
            # is_ready (not just is_leader): a fresh leader that has not yet
            # replayed inherited commits would miss write-write conflicts
            # against versions newer than the tx snapshot (lost update)
            raise NotMaster(f"ls {ls_id} not a ready leader on node "
                            f"{self.node_id}", ls_id=ls_id)
        m = Mutation(tablet_id, key, op, values)
        try:
            r.stage_locally(ctx.tx_id, ctx.read_snapshot, m)
        except WriteConflict:
            self.abort(ctx)
            raise
        ctx.mutations.setdefault(ls_id, []).append(m)

    def read(self, ctx: TxContext, ls_id: int, tablet_id: int,
             columns: list[str] | None = None, ranges=None):
        """Snapshot read (sees own staged writes via tx_id)."""
        r = self.replicas[ls_id]
        if not r.is_ready:
            # a fresh leader must finish replaying inherited committed
            # entries before serving, else reads miss rows
            raise NotMaster(f"ls {ls_id} replica on node {self.node_id} "
                            f"not a ready leader", ls_id=ls_id)
        return r.tablets[tablet_id].scan(
            ctx.read_snapshot, columns=columns, ranges=ranges, tx_id=ctx.tx_id
        )

    def commit(self, ctx: TxContext) -> None:
        """Start commit; terminal state arrives via apply callbacks
        (poll ctx.is_done under a drive loop, or block in live runtimes)."""
        from ..share.errsim import debug_sync, errsim_point

        errsim_point("EN_TX_COMMIT")
        debug_sync("BEFORE_COMMIT")
        if ctx.state is not TxState.ACTIVE:
            raise RuntimeError(f"tx {ctx.tx_id} is {ctx.state.value}")
        parts = [ls for ls, ms in ctx.mutations.items() if ms]
        if not parts:
            ctx.state = TxState.COMMITTED
            self._finish(ctx)
            return
        for ls in parts:
            if not self.replicas[ls].is_leader:
                self.abort(ctx)
                raise NotMaster(f"ls {ls} lost leadership before commit",
                                ls_id=ls)
        if len(parts) == 1:
            ls = parts[0]
            # version fetch + submit under gts.submit_lock: commit versions
            # land in the log nondecreasing, keeping entry scns a sound
            # follower-read watermark (see GtsService.submit_lock)
            with self.gts.submit_lock:
                rec = TxRecord(RecordType.REDO_COMMIT, ctx.tx_id,
                               tuple(ctx.mutations[ls]), self.gts.next_ts(),
                               dict_appends=tuple(ctx.dict_appends))
                # state moves BEFORE submit: apply can fire synchronously
                # inside submit_record (single-replica groups commit
                # immediately) and must find the ctx in COMMITTING
                ctx.commit_version = rec.commit_version
                ctx.state = TxState.COMMITTING
                try:
                    accepted = self.replicas[ls].submit_record(rec)
                except Exception:
                    # submit-path failure (EN_LOG_SUBMIT injection, IO error)
                    # before anything reached the log: roll back locally so
                    # the staged rows don't stay locked by a tx that can
                    # never decide — the orphan would block later writers
                    self._rollback(ctx, logged_ls=())
                    raise
            if accepted is None:
                # nothing reached the log: local rollback suffices
                self._rollback(ctx, logged_ls=())
                raise NotMaster(f"ls {ls} rejected submit", ls_id=ls)
            return
        # ---- 2PC
        ctx.state = TxState.PREPARING
        coord = parts[0]
        logged: list[int] = []
        for ls in parts:
            rec = TxRecord(RecordType.PREPARE, ctx.tx_id,
                           tuple(ctx.mutations[ls]), 0, coord, tuple(parts),
                           dict_appends=tuple(ctx.dict_appends))
            try:
                accepted = self.replicas[ls].submit_record(rec)
            except Exception:
                # submit-path failure mid-prepare: log ABORT where a
                # PREPARE already landed, release everything staged
                self._rollback(ctx, logged_ls=tuple(logged))
                raise
            if accepted is None:
                # some participants have a PREPARE in their log: log ABORT
                # there so replicas clean pending redo + tx tables
                self._rollback(ctx, logged_ls=tuple(logged))
                raise NotMaster(f"ls {ls} rejected prepare", ls_id=ls)
            logged.append(ls)

    # ------------------------------------------------------------- XA
    def xa_prepare(self, ctx: TxContext, xid: str, owner: str,
                   tenant: str = "") -> None:
        """Durable XA phase 1 (ob_trans_part_ctx.h:154 logs prepare through
        the part ctx): each participant's redo reaches its replicated log
        in an XA_PREPARE record tagged with the xid, then the tx PARKS in
        XA_PREPARED — no auto-commit; the external coordinator decides.
        Terminal XA_PREPARED arrives via apply callbacks (drive to it)."""
        if ctx.state is not TxState.ACTIVE:
            raise RuntimeError(f"tx {ctx.tx_id} is {ctx.state.value}")
        parts = [ls for ls, ms in ctx.mutations.items() if ms]
        if not parts:
            parts = [min(self.replicas)]  # empty branch: one marker record
        for ls in parts:
            if not self.replicas[ls].is_leader:
                self.abort(ctx)
                raise NotMaster(f"ls {ls} lost leadership before XA prepare",
                                ls_id=ls)
        ctx.xa_parts = tuple(parts)
        ctx.state = TxState.PREPARING
        logged: list[int] = []
        for ls in parts:
            rec = TxRecord(RecordType.XA_PREPARE, ctx.tx_id,
                           tuple(ctx.mutations.get(ls, ())), 0, parts[0],
                           tuple(parts), dict_appends=tuple(ctx.dict_appends),
                           xid=xid, owner=owner, tenant=tenant)
            if self.replicas[ls].submit_record(rec) is None:
                self._rollback(ctx, logged_ls=tuple(logged))
                raise NotMaster(f"ls {ls} rejected XA prepare", ls_id=ls)
            logged.append(ls)

    def xa_decide(self, ctx: TxContext, commit: bool) -> None:
        """External-coordinator decision for a parked (XA_PREPARED) branch.
        Commit logs COMMIT records with a fresh GTS version; replicas that
        staged the rows commit them, replicas (or a restarted node) holding
        only pending redo replay it. Either decision record rides the
        _undelivered/retry_decisions channel through transient non-leader
        windows — a dropped ABORT would leave the branch undecided in the
        log and resurrectable after a restart. Idempotent under retry of
        the SAME decision; reversing an in-flight decision is refused."""
        if ctx.state is TxState.COMMITTING and ctx.xa_decision is not None:
            if (ctx.xa_decision == "commit") != commit:
                raise RuntimeError(
                    f"tx {ctx.tx_id} already deciding "
                    f"{ctx.xa_decision}; cannot reverse")
            return  # retry: caller re-drives retry_decisions
        if ctx.state is not TxState.XA_PREPARED:
            raise RuntimeError(f"tx {ctx.tx_id} is {ctx.state.value}")
        ctx.xa_decision = "commit" if commit else "rollback"
        with self.gts.submit_lock:
            ctx.commit_version = self.gts.next_ts() if commit else 0
            ctx.state = TxState.COMMITTING  # decision (either way) in flight
            if not commit:
                for ls in ctx.mutations:
                    self.replicas[ls].abort_locally(ctx.tx_id)
            rtype = RecordType.COMMIT if commit else RecordType.ABORT
            for ls in ctx.xa_parts:
                rec = TxRecord(rtype, ctx.tx_id, (), ctx.commit_version)
                if self.replicas[ls].submit_record(rec) is None:
                    ctx._undelivered[ls] = rec

    def ensure_tx_id_above(self, floor: int) -> None:
        """Restart recovery: a recovered (still-undecided) XA branch keeps
        its pre-crash tx_id; the fresh counter must never re-issue it —
        a collision would let an unrelated new transaction adopt the
        branch's locks and re-staged rows."""
        nxt = next(self._tx_ids)
        self._tx_ids = itertools.count(max(nxt, floor + 1))

    def abort(self, ctx: TxContext) -> None:
        """Client-driven abort. Refused once the decision is in flight: a tx
        in COMMITTING has decisive records submitted to the log and MUST
        converge to COMMITTED (aborting it locally would diverge from
        followers that apply those records)."""
        if ctx.is_done:
            return
        if ctx.state is TxState.COMMITTING:
            raise RuntimeError(
                f"tx {ctx.tx_id} commit already in flight; cannot abort"
            )
        logged = (
            tuple(set(ctx.mutations) | set(ctx.xa_parts))
            if ctx.state in (TxState.PREPARING, TxState.XA_PREPARED)
            else ()
        )
        self._rollback(ctx, logged_ls=logged)

    def retry_decisions(self, ctx: TxContext) -> None:
        """Resubmit COMMIT decisions rejected by a transient non-leader
        window (driven from commit wait loops). If leadership moved to
        another NODE, resubmitting here cannot succeed — resolving that
        needs participant-driven recovery through the location service
        (prepared participants ask the coordinator log for the outcome);
        until then commit_sync surfaces it as a timeout, never as an abort.
        """
        if ctx.state is not TxState.COMMITTING:
            return
        for ls in list(ctx._undelivered):
            if self.replicas[ls].submit_record(ctx._undelivered[ls]) is not None:
                del ctx._undelivered[ls]

    def _rollback(self, ctx: TxContext, logged_ls: tuple[int, ...]) -> None:
        for ls in ctx.mutations:
            self.replicas[ls].abort_locally(ctx.tx_id)
        for ls in logged_ls:
            self.replicas[ls].submit_record(TxRecord(RecordType.ABORT, ctx.tx_id))
        ctx.state = TxState.ABORTED
        self._finish(ctx)

    # ------------------------------------------------- apply-event engine
    def _on_applied(self, ls_id: int, tx_id: int, rtype: RecordType, version: int) -> None:
        with self._lock:
            ctx = self._txs.get(tx_id)
        if ctx is None or ctx.is_done:
            return
        if rtype is RecordType.REDO_COMMIT:
            ctx.commit_version = version
            ctx.state = TxState.COMMITTED
            self._finish(ctx)
        elif rtype is RecordType.XA_PREPARE and ctx.state is TxState.PREPARING:
            # XA: record prepared parts, park when all are in — NEVER
            # auto-commit (that is the external coordinator's call)
            ctx._prepared.add(ls_id)
            if ctx._prepared >= set(ctx.xa_parts):
                ctx.state = TxState.XA_PREPARED
        elif rtype is RecordType.PREPARE and ctx.state is TxState.PREPARING:
            ctx._prepared.add(ls_id)
            if ctx._prepared >= set(ctx.mutations.keys()):
                # version fetch + COMMIT fan-out atomically vs other
                # committers (watermark invariant, GtsService.submit_lock)
                with self.gts.submit_lock:
                    ctx.commit_version = self.gts.next_ts()
                    ctx.state = TxState.COMMITTING
                    for ls in ctx.mutations:
                        rec = TxRecord(RecordType.COMMIT, ctx.tx_id, (),
                                       ctx.commit_version)
                        if self.replicas[ls].submit_record(rec) is None:
                            ctx._undelivered[ls] = rec
        elif rtype is RecordType.COMMIT and ctx.state is TxState.COMMITTING:
            ctx._committed_ls.add(ls_id)
            if ctx._committed_ls >= set(ctx.mutations.keys()):
                ctx.state = TxState.COMMITTED
                self._finish(ctx)
        elif rtype is RecordType.ABORT:
            if ctx.xa_parts and ctx.state is TxState.COMMITTING:
                # XA rollback decision: like commit, it is final only when
                # the ABORT record has applied on EVERY participant (the
                # caller's drive loop retries undelivered submissions)
                ctx._committed_ls.add(ls_id)
                if ctx._committed_ls >= set(ctx.xa_parts):
                    ctx.state = TxState.ABORTED
                    self._finish(ctx)
            else:
                ctx.state = TxState.ABORTED
                self._finish(ctx)

    def _finish(self, ctx: TxContext) -> None:
        with self._lock:
            self._txs.pop(ctx.tx_id, None)
