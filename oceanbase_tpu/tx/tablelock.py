"""Table locks + deadlock detection.

Reference surface: storage/tablelock (table/partition lock objects taken
inside transactions, released at tx end) and share/deadlock — the LCL
(lock-chain-length) distributed deadlock detection that finds wait cycles
and kills one participant.

Rebuild semantics: S/X locks on arbitrary lock ids (table tablet ids), one
outstanding wait per tx. `lock()` either grants, or registers the wait
edge and raises WouldBlock so the caller retries after the holder ends —
the deterministic analog of queueing on the lock-wait manager. Before
raising WouldBlock the manager walks the wait-for graph; a cycle aborts
the REQUESTER with DeadlockDetected (the youngest-tx victim policy: the
cycle closer is by construction the newest edge)."""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field


class LockMode(enum.IntEnum):
    ROW_X = 0  # intention-exclusive: taken implicitly by DML
    SHARE = 1  # LOCK TABLE ... IN SHARE MODE (blocks writes)
    EXCLUSIVE = 2  # LOCK TABLE ... IN EXCLUSIVE MODE (blocks everything)


# requested-vs-held compatibility (symmetric): IX+IX coexist (row conflicts
# are the memtable's job); S+S coexist; X conflicts with all
_COMPAT = {
    (LockMode.ROW_X, LockMode.ROW_X): True,
    (LockMode.SHARE, LockMode.SHARE): True,
}


class WouldBlock(Exception):
    """Lock held in a conflicting mode; retry after the holder finishes."""


class DeadlockDetected(Exception):
    """Granting this wait would close a wait-for cycle; abort the tx."""


@dataclass
class LockManager:
    # lock_id -> {tx_id: set of granted base modes}. A tx may hold several
    # base modes at once (SHARE + ROW_X == the SIX combination); keeping the
    # set — instead of one "max" enum — means upgrades are checked against
    # other holders per base mode, never granted by enum comparison.
    _granted: dict[object, dict[int, set[LockMode]]] = field(default_factory=dict)
    # tx_id -> (lock_id, mode) one outstanding wait
    _waiting: dict[int, tuple[object, LockMode]] = field(default_factory=dict)
    _lock: threading.RLock = field(default_factory=threading.RLock)
    # txs killed by the distributed detector (share/deadlock): surfaced as
    # DeadlockDetected on the victim's next lock() retry
    _aborted: set[int] = field(default_factory=set)
    # tx -> wait instance counter, bumped on each (re)registered wait:
    # lets the distributed detector drop probes from superseded waits
    # (the classic CMH phantom-cycle hazard)
    _wait_seq: dict[int, int] = field(default_factory=dict)
    deadlocks: int = 0

    @staticmethod
    def _compatible(a: LockMode, b: LockMode) -> bool:
        return _COMPAT.get((a, b), False)

    def _conflicting_holders(self, tx_id: int, lock_id, mode) -> set[int]:
        return {
            t for t, ms in self._granted.get(lock_id, {}).items()
            if t != tx_id and any(not self._compatible(mode, m) for m in ms)
        }

    def _wait_edges(self, tx_id: int) -> set[int]:
        """Who tx_id waits for (via its registered wait)."""
        w = self._waiting.get(tx_id)
        if w is None:
            return set()
        return self._conflicting_holders(tx_id, w[0], w[1])

    def _would_deadlock(self, start_tx: int) -> bool:
        """DFS over the wait-for graph from start_tx back to itself."""
        seen = set()
        stack = list(self._wait_edges(start_tx))
        while stack:
            t = stack.pop()
            if t == start_tx:
                return True
            if t in seen:
                continue
            seen.add(t)
            stack.extend(self._wait_edges(t))
        return False

    # ---------------------------------------- distributed-detector hooks
    def waiting_snapshot(self) -> dict[int, set[int]]:
        """tx -> conflicting holder txs, for every locally-waiting tx."""
        with self._lock:
            return {t: self._wait_edges(t) for t in list(self._waiting)}

    def wait_edges_of(self, tx_id: int) -> set[int]:
        with self._lock:
            return self._wait_edges(tx_id)

    def hosts_wait(self, tx_id: int) -> bool:
        with self._lock:
            return tx_id in self._waiting

    def wait_token(self, tx_id: int) -> int | None:
        """Current wait-instance token of a waiting tx (None = not
        waiting). A probe stamped with an older token chased a wait
        that no longer exists and must not abort anyone."""
        with self._lock:
            if tx_id not in self._waiting:
                return None
            return self._wait_seq.get(tx_id, 0)

    def abort(self, tx_id: int) -> None:
        """Mark a tx as a deadlock victim (distributed detector verdict);
        its next lock() retry raises DeadlockDetected."""
        with self._lock:
            self.deadlocks += 1
            self._aborted.add(tx_id)
            self._waiting.pop(tx_id, None)

    # -------------------------------------------------------------- API
    def lock(self, tx_id: int, lock_id, mode: LockMode) -> None:
        """Grant, or raise WouldBlock/DeadlockDetected."""
        with self._lock:
            if tx_id in self._aborted:
                self._aborted.discard(tx_id)
                raise DeadlockDetected(
                    f"tx {tx_id} chosen as distributed deadlock victim"
                )
            holders = self._granted.setdefault(lock_id, {})
            held = holders.get(tx_id, set())
            if mode in held or LockMode.EXCLUSIVE in held:
                return  # this exact strength (or a superset) already granted
            conflicts = self._conflicting_holders(tx_id, lock_id, mode)
            if not conflicts:
                holders.setdefault(tx_id, set()).add(mode)
                self._waiting.pop(tx_id, None)
                return
            if self._waiting.get(tx_id) != (lock_id, mode):
                self._wait_seq[tx_id] = self._wait_seq.get(tx_id, 0) + 1
            self._waiting[tx_id] = (lock_id, mode)
            if self._would_deadlock(tx_id):
                self.deadlocks += 1
                self._waiting.pop(tx_id, None)
                raise DeadlockDetected(
                    f"tx {tx_id} waiting on {lock_id} closes a cycle"
                )
            raise WouldBlock(
                f"lock {lock_id} held by {sorted(conflicts)}"
            )

    def release_all(self, tx_id: int) -> None:
        with self._lock:
            self._waiting.pop(tx_id, None)
            self._aborted.discard(tx_id)
            for lock_id in [
                k for k, hs in self._granted.items() if tx_id in hs
            ]:
                hs = self._granted[lock_id]
                del hs[tx_id]
                if not hs:
                    del self._granted[lock_id]

    def holders(self, lock_id) -> dict[int, LockMode]:
        """Strongest base mode per holder (display/assert surface)."""
        with self._lock:
            return {
                t: max(ms) for t, ms in self._granted.get(lock_id, {}).items()
            }
