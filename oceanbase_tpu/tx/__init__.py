"""Transactions: GTS, log streams, snapshot isolation, 1PC/2PC.

Layer map (SURVEY.md §2.3 storage/tx + §2.4 -> rebuild):
  gts.py      per-tenant timestamp authority
  records.py  tx log record formats (redo/prepare/commit/abort)
  ls.py       log stream replica: tablets + palf + apply/replay
  txn.py      TransService: tx contexts, conflicts, 1PC/2PC state machine
  cluster.py  in-process multi-replica cluster harness
"""

from .cluster import LocalCluster
from .gts import GtsService
from .ls import LSReplica, make_ls_group
from .records import Mutation, RecordType, TxRecord
from .txn import NotMaster, TransService, TxContext, TxState

__all__ = [
    "GtsService",
    "LSReplica",
    "make_ls_group",
    "Mutation",
    "RecordType",
    "TxRecord",
    "TransService",
    "TxContext",
    "TxState",
    "NotMaster",
    "LocalCluster",
]
