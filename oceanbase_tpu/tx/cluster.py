"""LocalCluster: an in-process multi-replica cluster harness.

Reference surface: the reference's test env pyramid (SURVEY.md §4) — mittest
MockTenantModuleEnv (tier 2) and the 3-zone forked cluster (tier 4,
mittest/multi_replica). The rebuild gets both from one harness: N "nodes"
(replica sets) share a virtual-clock LocalBus; each LS replicates across all
nodes; a TransService per node. `drive_until` pumps ticks + delivery, so
tests and single-process deployments (the SQL engine's DML path) run the
full consensus + tx stack deterministically with zero threads.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.dtypes import Schema
from ..ha.detect import KA_BASE, NetKeepAlive
from ..log import LocalBus, leader_of, run_until
from .gts import GtsService
from .ls import LSReplica, make_ls_group
from .txn import TransService, TxContext


@dataclass
class LocalCluster:
    n_nodes: int = 3
    bus: LocalBus = None  # type: ignore[assignment]
    gts: GtsService = None  # type: ignore[assignment]
    ls_groups: dict[int, dict[int, LSReplica]] = field(default_factory=dict)
    services: dict[int, TransService] = field(default_factory=dict)
    # durable mode: palf logs live under {data_dir}/n{node}/ls_{ls}
    data_dir: str | None = None
    fsync: bool = True
    # multi-tenant record observation: when several tenants share this
    # cluster, each registers here and a dispatcher fans records out
    # (each observer ignores tablets it does not own)
    record_observers: list = field(default_factory=list)
    # per-node keepalive endpoints (ha/detect.NetKeepAlive) riding the
    # drive loop; dead-peer evidence feeds the ls-replica virtual table,
    # the health sentinel and rootserver rebalancing
    keepalives: dict[int, NetKeepAlive] = field(default_factory=dict)
    _next_ls_base: int = 0

    def __post_init__(self):
        if self.bus is None:
            self.bus = LocalBus()
        if self.gts is None:
            # GTS rides the virtual clock so timestamps are deterministic
            self.gts = GtsService(clock=lambda: self.bus.now)

    # ------------------------------------------------------------- build
    def create_ls(self, ls_id: int) -> dict[int, LSReplica]:
        group = make_ls_group(
            ls_id, list(range(self.n_nodes)), self.bus,
            palf_id_base=self._next_ls_base,
            data_dir=self.data_dir, fsync=self.fsync,
        )
        self._next_ls_base += 1000
        self.ls_groups[ls_id] = group
        return group

    def create_tablet(self, ls_id: int, tablet_id: int, schema: Schema,
                      key_cols: list[str]) -> None:
        for rep in self.ls_groups[ls_id].values():
            rep.create_tablet(tablet_id, schema, key_cols)

    def finalize(self) -> None:
        """Build per-node TransServices and elect initial leaders."""
        nodes = list(range(self.n_nodes))
        for n in nodes:
            self.services[n] = TransService(
                n, self.gts, {ls: g[n] for ls, g in self.ls_groups.items()}
            )
            if n not in self.keepalives:
                self.keepalives[n] = NetKeepAlive(self.bus, n, nodes)
        self.elect_all()

    def add_node(self, node: int) -> None:
        """Join an empty node (no replicas yet); the balance loop
        (ha/migrate.balance_cluster) migrates replicas onto it."""
        if node in self.services:
            raise ValueError(f"node {node} already exists")
        self.services[node] = TransService(node, self.gts, {})
        self.n_nodes = max(self.n_nodes, node + 1)

    # ------------------------------------------------------------- drive
    def _palfs(self):
        return [r.palf for g in self.ls_groups.values() for r in g.values()]

    def _tickables(self):
        # keepalives share the palf drive loop: run_until only needs .tick()
        return self._palfs() + list(self.keepalives.values())

    def drive_until(self, cond, max_time: float = 30.0) -> bool:
        return run_until(self.bus, self._tickables(), cond, max_time=max_time)

    def settle(self, t: float = 1.0) -> None:
        self.drive_until(lambda: False, max_time=t)

    def elect_all(self) -> None:
        for ls_id, group in self.ls_groups.items():
            ok = self.drive_until(
                lambda g=group: any(r.is_ready for r in g.values())
            )
            if not ok:
                raise RuntimeError(f"ls {ls_id}: no ready leader elected")

    # ----------------------------------------------------------- routing
    def leader_node(self, ls_id: int, max_time: float = 15.0) -> int:
        """Node of the ls's READY leader, driving the clock until one exists
        (a fresh leader needs its no-op committed + replay caught up)."""
        group = self.ls_groups[ls_id]
        ok = self.drive_until(
            lambda: any(r.is_ready for r in group.values()), max_time=max_time
        )
        if not ok:
            raise RuntimeError(f"ls {ls_id}: no ready leader")
        for node, rep in group.items():
            if rep.is_ready:
                return node
        raise AssertionError

    def kill_node(self, node: int, settle: float = 1.0) -> None:
        """Disconnect a node and advance time past the lease window so its
        leader replicas notice and step down (a killed process's clients see
        silence; the virtual-clock analog needs the clock to move)."""
        for group in self.ls_groups.values():
            self.bus.kill(group[node].palf.node_id)
        if node in self.keepalives:
            self.bus.kill(KA_BASE + node)
        self.settle(settle)

    def revive_node(self, node: int, settle: float = 1.0) -> None:
        """Reconnect a killed node's replicas + keepalive endpoint and let
        the cluster settle so they catch up (rolling-restart recovery)."""
        for group in self.ls_groups.values():
            self.bus.revive(group[node].palf.node_id)
            # rejoin grace: wait a lease window for the incumbent's
            # heartbeat instead of campaigning off the stale timer and
            # deposing a healthy leader (restart disruption)
            group[node].palf.reset_election_timer()
        if node in self.keepalives:
            self.bus.revive(KA_BASE + node)
        self.settle(settle)

    def unreachable_nodes(self) -> set[int]:
        """Majority keepalive vote: node d is unreachable when more than
        half of the OTHER nodes' keepalives have lost it (a one-link
        partition never indicts a node; a kill always does)."""
        out: set[int] = set()
        for d in self.keepalives:
            observers = [ka for n, ka in self.keepalives.items() if n != d]
            if not observers:
                continue
            votes = sum(1 for ka in observers if ka.is_dead(d))
            if votes >= len(observers) // 2 + 1:
                out.add(d)
        return out

    def transfer_leader(self, ls_id: int, target_node: int,
                        max_time: float = 10.0) -> None:
        """Move ls leadership to target_node (palf TimeoutNow handshake)."""
        group = self.ls_groups[ls_id]
        target_addr = group[target_node].palf.node_id

        def try_transfer():
            lead = leader_of([r.palf for r in group.values()])
            if lead is not None and lead.node_id == target_addr:
                return True
            if lead is not None:
                lead.transfer_leader(target_addr)
            return False

        if not run_until(self.bus, self._tickables(), try_transfer, max_time=max_time):
            raise TimeoutError(f"ls {ls_id}: leader transfer to node {target_node} failed")

    def service_for(self, *ls_ids: int) -> TransService:
        """A TransService on a node leading ALL given LS.

        A multi-LS transaction needs its coordinator on a node leading every
        participant (the rebuild's TransService talks only to local
        replicas); co-locate by transferring leadership to the first LS's
        leader node — the analog of the reference routing a query to a
        server hosting the participant leaders.
        """
        home = self.leader_node(ls_ids[0])
        for ls in ls_ids[1:]:
            if self.leader_node(ls) != home:
                self.transfer_leader(ls, home)
        return self.services[home]

    # ------------------------------------------------------- tx shortcuts
    def commit_sync(self, svc: TransService, ctx: TxContext,
                    max_time: float = 30.0) -> None:
        svc.commit(ctx)

        def done() -> bool:
            svc.retry_decisions(ctx)
            return ctx.is_done

        if not self.drive_until(done, max_time=max_time):
            raise TimeoutError(f"tx {ctx.tx_id} did not finish")
