"""Global timestamp service (GTS).

Reference surface: storage/tx ObTsMgr (ob_ts_mgr.h:358) + ObGtsSource
(ob_gts_source.h:69) — one timestamp authority per tenant serving strictly
increasing commit/read timestamps over RPC, with local caching. The rebuild
keeps one authority per tenant; timestamps are hybrid (wall-clock µs
max'd with a counter) so they are monotonic under clock skew and still
roughly wall-ordered. A `clock` callable injects virtual time in tests.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class GtsService:
    """The per-tenant timestamp authority (lives with the tenant's LS1 leader)."""

    clock: Callable[[], float] = time.time
    _last: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)
    # Serializes commit-version fetch + log submit (tx/txn.py holds it
    # around both). With the fetch and the append atomic, commit versions
    # appear in each LS log in nondecreasing order, so an applied entry's
    # scn = max(prev_scn+1, commit_version) dominates the commit version
    # of EVERY earlier decisive record — the invariant that makes a
    # replica's applied scn a sound follower-read watermark.
    submit_lock: threading.RLock = field(default_factory=threading.RLock)

    def next_ts(self) -> int:
        """Strictly increasing timestamp (µs domain)."""
        wall = int(self.clock() * 1_000_000)
        with self._lock:
            self._last = max(self._last + 1, wall)
            return self._last

    def current(self) -> int:
        """A read snapshot: >= every previously issued ts. Does NOT burn a
        sequence slot (ObTsMgr serves reads from its local cache the same
        way, ob_ts_mgr.h:358): the last issued ts already dominates every
        committed commit version, which is all a snapshot needs."""
        with self._lock:
            return self._last

    def advance_to(self, ts: int) -> None:
        """Fast-forward past restored/replayed history so new timestamps
        never collide below it (restore-time invariant)."""
        with self._lock:
            self._last = max(self._last, ts)
