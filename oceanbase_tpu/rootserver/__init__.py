"""Cluster management (reference: src/rootserver).

service.py  RootService-lite: bootstrap, DDL orchestration, tablet
            placement / balance reporting.
"""

from .service import RootService

__all__ = ["RootService"]
