"""RootService-lite: bootstrap, DDL orchestration, placement.

Reference surface: src/rootserver — cluster bootstrap (ob_bootstrap.cpp),
the DDL service through which every schema change flows
(ob_ddl_service.h:99), and load balancing (rootserver/balance). The
rebuild's RootService owns:

  * bootstrap: create the log streams and elect initial leaders;
  * DDL: allocate tablet ids, create tablets on every replica, publish the
    new schema through the multi-version SchemaService;
  * placement: least-loaded-LS choice for new tablets + a balance report
    (the decision side of the reference's balance groups; replica movement
    itself is the HA layer's job).
"""

from __future__ import annotations

import threading

from ..share.schema_service import SchemaError, SchemaService
from ..tx.cluster import LocalCluster


class RootService:
    def __init__(self, cluster: LocalCluster, schema: SchemaService):
        self.cluster = cluster
        self.schema = schema
        self.next_tablet_id = 200001  # plain int: restorable across restarts
        self._lock = threading.RLock()

    def _alloc_tablet_id(self) -> int:
        with self._lock:
            v = self.next_tablet_id
            self.next_tablet_id += 1
            return v

    # ---------------------------------------------------------- bootstrap
    @staticmethod
    def bootstrap(n_nodes: int, n_ls: int, data_dir: str | None = None,
                  fsync: bool = True,
                  finalize: bool = True) -> tuple[LocalCluster, "RootService"]:
        """Build the cluster. finalize=False defers TransService creation +
        initial election: a restarting node must recreate tablets and load
        storage checkpoints BEFORE commit/replay can run (the reference's
        staged ObServer::init ordering — storage before log service start,
        ob_server.cpp:232/923)."""
        cluster = LocalCluster(n_nodes=n_nodes, data_dir=data_dir, fsync=fsync)
        for ls in range(1, n_ls + 1):
            cluster.create_ls(ls)
        if finalize:
            cluster.finalize()
        return cluster, RootService(cluster, SchemaService())

    # ---------------------------------------------------------- placement
    def tablet_counts(self) -> dict[int, int]:
        """Tablets per LS (from any replica; groups are symmetric)."""
        out = {}
        for ls_id, group in self.cluster.ls_groups.items():
            rep = next(iter(group.values()))
            out[ls_id] = len(rep.tablets)
        return out

    def choose_ls(self) -> int:
        counts = self.tablet_counts()
        return min(sorted(counts), key=lambda ls: counts[ls])

    # ---------------------------------------------------------------- DDL
    def create_table(self, info_factory, n_partitions: int = 1) -> object:
        """Run a CREATE TABLE: pick placement for every partition (least-
        loaded LS round-robin), build the TableInfo via
        `info_factory(partitions)` with partitions = [(ls_id, tablet_id)],
        create tablets on all replicas, publish the schema version."""
        with self._lock:
            partitions = []
            counts = self.tablet_counts()
            for _ in range(max(1, n_partitions)):
                ls_id = min(sorted(counts), key=lambda ls: counts[ls])
                counts[ls_id] += 1
                partitions.append((ls_id, self._alloc_tablet_id()))
            ti = info_factory(partitions)

            def mutate(tables: dict):
                if ti.name in tables:
                    raise SchemaError(f"table {ti.name} already exists")
                tables[ti.name] = ti

            for ls_id, tablet_id in partitions:
                self.cluster.create_tablet(
                    ls_id, tablet_id, ti.schema, ti.key_cols
                )
            try:
                ti.schema_version = self.schema.apply_ddl(mutate)
            except SchemaError:
                for ls_id, tablet_id in partitions:
                    for rep in self.cluster.ls_groups[ls_id].values():
                        rep.tablets.pop(tablet_id, None)
                raise
            return ti

    def create_index_tablet(self, ls_id: int, schema, key_cols) -> int:
        """Allocate and create an index tablet co-located with its base
        table's LS (same log stream => index maintenance stays 1PC)."""
        with self._lock:
            tablet_id = self._alloc_tablet_id()
        self.cluster.create_tablet(ls_id, tablet_id, schema, key_cols)
        return tablet_id

    def drop_table(self, name: str) -> object:
        with self._lock:
            dropped = {}

            def mutate(tables: dict):
                if name not in tables:
                    raise SchemaError(f"no such table {name}")
                dropped["ti"] = tables.pop(name)

            self.schema.apply_ddl(mutate)
            ti = dropped["ti"]
            for ls_id, tablet_id in getattr(
                ti, "partitions", [(ti.ls_id, ti.tablet_id)]
            ):
                for rep in self.cluster.ls_groups[ls_id].values():
                    rep.tablets.pop(tablet_id, None)
            return ti
