"""RootService-lite: bootstrap, DDL orchestration, placement.

Reference surface: src/rootserver — cluster bootstrap (ob_bootstrap.cpp),
the DDL service through which every schema change flows
(ob_ddl_service.h:99), and load balancing (rootserver/balance). The
rebuild's RootService owns:

  * bootstrap: create the log streams and elect initial leaders;
  * DDL: allocate tablet ids, create tablets on every replica, publish the
    new schema through the multi-version SchemaService;
  * placement: least-loaded-LS choice for new tablets + a balance report
    (the decision side of the reference's balance groups; replica movement
    itself is the HA layer's job).
"""

from __future__ import annotations

import threading

from ..share.schema_service import SchemaError, SchemaService
from ..tx.cluster import LocalCluster


def plan_leader_moves(leader_map: dict[int, int],
                      replica_nodes: dict[int, list[int]],
                      alive: set[int],
                      spread: bool = False) -> list[tuple[int, int, int]]:
    """Pure leader-placement decision (the decision side of the
    reference's rootserver/balance leader coordinator). Returns
    [(ls_id, from_node, to_node)] such that applying every move leaves:

      * no LS led by a node outside `alive` (evacuation — FailureDetector
        evidence says the node is dead, don't wait for its lease to buy
        every client a NotMaster round-trip);
      * when `spread` (QoS ledger shows serving pressure), leader counts
        across alive nodes differing by at most 1 (each alive node's
        worker pool carries its fair share of the strong-read load).

    Deterministic: ties break toward the lowest node id, LS are visited
    in id order — same inputs, same plan, replayable from a bench log.
    """
    moves: list[tuple[int, int, int]] = []
    counts = {n: 0 for n in sorted(alive)}
    for _ls, n in leader_map.items():
        if n in counts:
            counts[n] += 1
    if not counts:
        return moves

    def least_loaded(cands: list[int]) -> int | None:
        live = [c for c in cands if c in counts]
        return min(live, key=lambda c: (counts[c], c)) if live else None

    # 1. evacuation: any LS led by a dead node moves to the least-loaded
    #    alive replica holder
    for ls_id in sorted(leader_map):
        frm = leader_map[ls_id]
        if frm in alive:
            continue
        to = least_loaded(replica_nodes.get(ls_id, []))
        if to is None:
            continue
        moves.append((ls_id, frm, to))
        counts[to] += 1

    # 2. spread under pressure: peel leaders off the most-loaded node
    #    while the imbalance is observable (diff >= 2)
    if spread:
        placed = {ls: to for ls, _f, to in moves}
        lead_at = {ls: placed.get(ls, n) for ls, n in leader_map.items()}
        while True:
            hi = max(counts, key=lambda c: (counts[c], -c))
            lo = min(counts, key=lambda c: (counts[c], c))
            if counts[hi] - counts[lo] < 2:
                break
            cand = next(
                (ls for ls in sorted(lead_at)
                 if lead_at[ls] == hi and ls not in placed
                 and lo in replica_nodes.get(ls, [])),
                None)
            if cand is None:
                break
            moves.append((cand, hi, lo))
            placed[cand] = lo
            lead_at[cand] = lo
            counts[hi] -= 1
            counts[lo] += 1
    return moves


class RootService:
    def __init__(self, cluster: LocalCluster, schema: SchemaService):
        self.cluster = cluster
        self.schema = schema
        self.next_tablet_id = 200001  # plain int: restorable across restarts
        self._lock = threading.RLock()

    def _alloc_tablet_id(self) -> int:
        with self._lock:
            v = self.next_tablet_id
            self.next_tablet_id += 1
            return v

    # ---------------------------------------------------------- bootstrap
    @staticmethod
    def bootstrap(n_nodes: int, n_ls: int, data_dir: str | None = None,
                  fsync: bool = True,
                  finalize: bool = True) -> tuple[LocalCluster, "RootService"]:
        """Build the cluster. finalize=False defers TransService creation +
        initial election: a restarting node must recreate tablets and load
        storage checkpoints BEFORE commit/replay can run (the reference's
        staged ObServer::init ordering — storage before log service start,
        ob_server.cpp:232/923)."""
        cluster = LocalCluster(n_nodes=n_nodes, data_dir=data_dir, fsync=fsync)
        for ls in range(1, n_ls + 1):
            cluster.create_ls(ls)
        if finalize:
            cluster.finalize()
        return cluster, RootService(cluster, SchemaService())

    # ---------------------------------------------------------- placement
    def tablet_counts(self) -> dict[int, int]:
        """Tablets per LS (from any replica; groups are symmetric)."""
        out = {}
        for ls_id, group in self.cluster.ls_groups.items():
            rep = next(iter(group.values()))
            out[ls_id] = len(rep.tablets)
        return out

    def choose_ls(self) -> int:
        counts = self.tablet_counts()
        return min(sorted(counts), key=lambda ls: counts[ls])

    # ------------------------------------------------------ leader balance
    def leader_map(self) -> dict[int, int]:
        """ls_id -> node currently holding palf leadership. LS mid-election
        (no leader) are omitted — there is nothing to move yet and the
        election will place one without rootserver help."""
        from ..log.palf import leader_of

        out: dict[int, int] = {}
        for ls_id, group in self.cluster.ls_groups.items():
            lead = leader_of([r.palf for r in group.values()])
            if lead is None:
                continue
            for node, rep in group.items():
                if rep.palf is lead:
                    out[ls_id] = node
                    break
        return out

    def balance_leaders(self, unreachable: set[int] = frozenset(),
                        spread: bool = False) -> list[tuple[int, int, int]]:
        """Decide leader moves from FailureDetector evidence (`unreachable`,
        the keepalive majority vote) and serving pressure (`spread`, from
        the tenant QoS ledger). Pure decision — the caller applies the
        moves (Database queues them as background dags)."""
        alive = set(range(self.cluster.n_nodes)) - set(unreachable)
        replica_nodes = {
            ls: sorted(group) for ls, group in self.cluster.ls_groups.items()
        }
        return plan_leader_moves(self.leader_map(), replica_nodes, alive,
                                 spread=spread)

    # ---------------------------------------------------------------- DDL
    def create_table(self, info_factory, n_partitions: int = 1) -> object:
        """Run a CREATE TABLE: pick placement for every partition (least-
        loaded LS round-robin), build the TableInfo via
        `info_factory(partitions)` with partitions = [(ls_id, tablet_id)],
        create tablets on all replicas, publish the schema version."""
        with self._lock:
            partitions = []
            counts = self.tablet_counts()
            for _ in range(max(1, n_partitions)):
                ls_id = min(sorted(counts), key=lambda ls: counts[ls])
                counts[ls_id] += 1
                partitions.append((ls_id, self._alloc_tablet_id()))
            ti = info_factory(partitions)

            def mutate(tables: dict):
                if ti.name in tables:
                    raise SchemaError(f"table {ti.name} already exists")
                tables[ti.name] = ti

            for ls_id, tablet_id in partitions:
                self.cluster.create_tablet(
                    ls_id, tablet_id, ti.schema, ti.key_cols
                )
            try:
                ti.schema_version = self.schema.apply_ddl(mutate)
            except SchemaError:
                for ls_id, tablet_id in partitions:
                    for rep in self.cluster.ls_groups[ls_id].values():
                        rep.tablets.pop(tablet_id, None)
                raise
            return ti

    def create_index_tablet(self, ls_id: int, schema, key_cols) -> int:
        """Allocate and create an index tablet co-located with its base
        table's LS (same log stream => index maintenance stays 1PC)."""
        with self._lock:
            tablet_id = self._alloc_tablet_id()
        self.cluster.create_tablet(ls_id, tablet_id, schema, key_cols)
        return tablet_id

    def drop_table(self, name: str) -> object:
        with self._lock:
            dropped = {}

            def mutate(tables: dict):
                if name not in tables:
                    raise SchemaError(f"no such table {name}")
                dropped["ti"] = tables.pop(name)

            self.schema.apply_ddl(mutate)
            ti = dropped["ti"]
            for ls_id, tablet_id in getattr(
                ti, "partitions", [(ti.ls_id, ti.tablet_id)]
            ):
                for rep in self.cluster.ls_groups[ls_id].values():
                    rep.tablets.pop(tablet_id, None)
            return ti
