"""Host-side tables: the CPU anchor of the storage/compute bridge.

A Table owns numpy column arrays + dictionaries and produces device
ColumnBatch views. This is the marshalling boundary the north star names:
the reference decodes micro-blocks directly into expression vectors
(storage/blocksstable/ob_imicro_block_reader.h:506-552 get_rows into
exprs+eval_ctx); here the storage layer (oceanbase_tpu/storage) decodes into
Table columns and `to_batch()` ships them to HBM once, after which all query
execution stays on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .column import ColumnBatch, make_batch
from .dictionary import Dictionary
from .dtypes import DataType, Field, Schema, TypeKind


@dataclass
class Table:
    name: str
    schema: Schema
    data: dict[str, np.ndarray] = field(default_factory=dict)
    dicts: dict[str, Dictionary] = field(default_factory=dict)
    valid: dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def nrows(self) -> int:
        if not self.data:
            return 0
        return len(next(iter(self.data.values())))

    @staticmethod
    def from_pydict(
        name: str, schema: Schema, pydata: dict[str, list | np.ndarray]
    ) -> "Table":
        """Ingest python/numpy values; encodes VARCHAR via sorted dictionaries."""
        data: dict[str, np.ndarray] = {}
        dicts: dict[str, Dictionary] = {}
        for f in schema.fields:
            col = pydata[f.name]
            if f.dtype.kind is TypeKind.VARCHAR:
                arr = np.asarray(col)
                if arr.dtype.kind not in ("U", "S"):
                    # coerce everything (objects, numerics) to strings so
                    # np.unique sorts lexicographically and the sorted-dict
                    # invariant (code order == string order) holds
                    arr = arr.astype(str)
                d, codes = Dictionary.from_strings_bulk(arr)
                data[f.name] = codes
                dicts[f.name] = d
            elif f.dtype.is_decimal:
                a = np.asarray(col)
                if np.issubdtype(a.dtype, np.floating):
                    a = np.round(a * f.dtype.decimal_factor)
                data[f.name] = a.astype(f.dtype.storage_np)
            else:
                data[f.name] = np.asarray(col, dtype=f.dtype.storage_np)
        return Table(name, schema, data, dicts)

    def to_batch(self, capacity: int | None = None) -> ColumnBatch:
        return make_batch(
            self.data, self.schema, self.dicts, capacity=capacity, valid=self.valid
        )

    def column_as_python(self, name: str):
        """Decode a column to python values (strings/decimals) for display."""
        dt = self.schema[name]
        a = self.data[name]
        if dt.kind is TypeKind.VARCHAR and name in self.dicts:
            return self.dicts[name].decode(a)
        if dt.is_decimal:
            return a.astype(np.float64) / dt.decimal_factor
        return a
