"""Host-side global string dictionaries.

Strings never reach the TPU: every VARCHAR column is dictionary-encoded at
load/ingest time into int32 codes, with the code->string mapping kept on the
host. Joins and group-bys on strings become integer problems on device.

Reference precedent: OceanBase's per-micro-block dictionary encodings
(storage/blocksstable/encoding/ob_dict_decoder_simd.cpp and
cs_encoding/ob_dict_column_decoder_simd.cpp). The TPU redesign promotes the
dictionary from a block-local compression detail to the *global* physical
representation of the column, because device kernels cannot chase varlen
bytes.

Two dictionary flavors:

- Dictionary: insertion-ordered, codes are arbitrary. O(1) encode.
- SortedDictionary: codes are assigned in lexicographic order so that
  code comparison == string comparison; required when range predicates
  (<, >, BETWEEN, ORDER BY) apply to the column. Built by finalizing an
  unsorted dictionary.
"""

from __future__ import annotations

import numpy as np


class Dictionary:
    """Insertion-ordered string <-> int32 code mapping."""

    __slots__ = ("_values", "_index", "sorted")

    def __init__(self, values: list[str] | None = None, sorted_: bool = False):
        self._values: list[str] = list(values) if values else []
        self._index: dict[str, int] = {v: i for i, v in enumerate(self._values)}
        self.sorted = sorted_

    def __len__(self) -> int:
        return len(self._values)

    def encode_one(self, s: str, add: bool = True) -> int:
        code = self._index.get(s)
        if code is None:
            if not add:
                return -1
            code = len(self._values)
            self._values.append(s)
            self._index[s] = code
            self.sorted = self.sorted and (
                len(self._values) < 2 or self._values[-2] <= s
            )
        return code

    def encode(self, strings, add: bool = True) -> np.ndarray:
        return np.fromiter(
            (self.encode_one(s, add) for s in strings),
            dtype=np.int32,
            count=len(strings),
        )

    def decode_one(self, code: int) -> str:
        return self._values[code]

    def decode(self, codes: np.ndarray) -> list[str]:
        vals = self._values
        return [vals[c] if c >= 0 else None for c in codes]

    def values(self) -> list[str]:
        return list(self._values)

    @staticmethod
    def from_strings_bulk(strings: np.ndarray) -> tuple["Dictionary", np.ndarray]:
        """Vectorized build: unique+inverse in one numpy pass.

        Returns a SORTED dictionary (np.unique sorts) and int32 codes.
        ~100x faster than per-item encode for multi-million-row ingest.
        """
        values, codes = np.unique(np.asarray(strings), return_inverse=True)
        return Dictionary([str(v) for v in values], sorted_=True), codes.astype(
            np.int32
        )

    @staticmethod
    def merge(
        dl: "Dictionary | None", dr: "Dictionary | None"
    ) -> tuple["Dictionary | None", np.ndarray | None, np.ndarray | None]:
        """Common dictionary for combining two dict-encoded columns (set
        operations, cross-table comparisons). Returns (merged, remap_left,
        remap_right); a None remap means codes pass through unchanged."""
        if dr is None or dl is dr:
            return dl, None, None
        if dl is None:
            return dr, None, None
        if dl._values == dr._values:
            return dl, None, None
        merged_vals = sorted(set(dl._values) | set(dr._values))
        merged = Dictionary(merged_vals, sorted_=True)
        lmap = np.fromiter(
            (merged._index[v] for v in dl._values), np.int32, len(dl._values)
        )
        rmap = np.fromiter(
            (merged._index[v] for v in dr._values), np.int32, len(dr._values)
        )
        return merged, lmap, rmap

    def finalize_sorted(self, codes: np.ndarray) -> tuple["Dictionary", np.ndarray]:
        """Return an order-preserving dictionary and remapped codes.

        After this, code order == lexicographic string order, enabling device
        range predicates and ORDER BY directly on codes.
        """
        order = np.argsort(np.asarray(self._values, dtype=object), kind="stable")
        remap = np.empty(len(self._values), dtype=np.int32)
        remap[order] = np.arange(len(self._values), dtype=np.int32)
        new_values = [self._values[i] for i in order]
        d = Dictionary(new_values, sorted_=True)
        return d, remap[codes]
