from .dtypes import (
    BOOL,
    DATE,
    FLOAT32,
    FLOAT64,
    INT8,
    INT16,
    INT32,
    INT64,
    TIMESTAMP,
    VARCHAR,
    DataType,
    Field,
    Schema,
    TypeKind,
    common_numeric_type,
)
from .dictionary import Dictionary
from .column import ColumnBatch, batch_to_host, make_batch
from .table import Table

__all__ = [
    "BOOL",
    "DATE",
    "FLOAT32",
    "FLOAT64",
    "INT8",
    "INT16",
    "INT32",
    "INT64",
    "TIMESTAMP",
    "VARCHAR",
    "DataType",
    "Field",
    "Schema",
    "TypeKind",
    "common_numeric_type",
    "Dictionary",
    "ColumnBatch",
    "batch_to_host",
    "make_batch",
    "Table",
]
