"""SQL type system mapped onto TPU-friendly physical representations.

Reference surface: OceanBase's ObObjType / ObObjMeta boxed-value type system
(deps/oblib/src/common/object/ob_object.h) and the datum width table
(src/share/datum/ob_datum.h:30). The rebuild collapses that 40+-type lattice
into a small set of *physical* representations chosen for the TPU:

- integers:   int8/16/32/64 device arrays (int64 is emulated on TPU as an
              int32 pair by XLA; kernels prefer the narrowest width that fits)
- floats:     float32 / float64 (f64 only on CPU paths; TPU kernels use f32)
- decimal:    scaled integers (DECIMAL(p,s) -> int32 if p-s small else int64).
              This mirrors the reference's own trick of storing decimals as
              integer words (lib/number) but with a fixed compile-time scale so
              arithmetic stays on the VPU/MXU with no per-value interpretation.
- date:       int32 days since 1970-01-01 (reference: ObDateType).
- varchar:    dictionary-encoded int32 codes + a host-side Dictionary
              (reference precedent: the dict encodings in
              storage/blocksstable/encoding/ob_dict_decoder_simd.cpp; here the
              dictionary is global per column so joins/group-bys on strings
              become integer problems on device).
- bool:       bool_ arrays (predicate masks are first-class; the analog of
              ObBitVector / ObBatchRows.skip_, src/sql/engine/ob_bit_vector.h).

Null handling: a separate validity bool array per column (True = present),
the SoA analog of ObDatum's null_ flag bit (src/share/datum/ob_datum.h:111).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np


class TypeKind(enum.Enum):
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    DECIMAL = "decimal"  # scaled integer
    DATE = "date"  # int32 days since epoch
    TIMESTAMP = "timestamp"  # int64 microseconds since epoch
    VARCHAR = "varchar"  # dict-encoded int32 codes
    VECTOR = "vector"  # fixed-dim float32 rows; precision = dimension


_INT_KINDS = {TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.INT64}


@dataclass(frozen=True)
class DataType:
    """A logical SQL type with a fixed physical representation.

    For DECIMAL, `precision`/`scale` follow SQL DECIMAL(p, s); the physical
    array holds value * 10**s as an integer of width `storage_np` (int32 when
    the scaled magnitude provably fits, else int64).
    """

    kind: TypeKind
    precision: int = 0
    scale: int = 0
    nullable: bool = False

    # ---- constructors ------------------------------------------------
    @staticmethod
    def bool_(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.BOOL, nullable=nullable)

    @staticmethod
    def int8(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.INT8, nullable=nullable)

    @staticmethod
    def int16(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.INT16, nullable=nullable)

    @staticmethod
    def int32(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.INT32, nullable=nullable)

    @staticmethod
    def int64(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.INT64, nullable=nullable)

    @staticmethod
    def float32(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.FLOAT32, nullable=nullable)

    @staticmethod
    def float64(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.FLOAT64, nullable=nullable)

    @staticmethod
    def decimal(precision: int, scale: int, nullable: bool = False) -> "DataType":
        if not (0 < precision <= 18 and 0 <= scale <= precision):
            raise ValueError(f"unsupported DECIMAL({precision},{scale})")
        return DataType(TypeKind.DECIMAL, precision, scale, nullable)

    @staticmethod
    def date(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.DATE, nullable=nullable)

    @staticmethod
    def timestamp(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.TIMESTAMP, nullable=nullable)

    @staticmethod
    def varchar(nullable: bool = False) -> "DataType":
        return DataType(TypeKind.VARCHAR, nullable=nullable)

    @staticmethod
    def vector(dim: int) -> "DataType":
        """Fixed-dimension embedding column: float32 rows of shape (dim,)
        (reference: src/storage/vector_index — obvec stores float arrays;
        here the whole column is one (n, dim) device matrix so distance
        scoring is a matmul on the MXU)."""
        return DataType(TypeKind.VECTOR, precision=dim)

    # ---- physical representation -------------------------------------
    @property
    def storage_np(self) -> np.dtype:
        k = self.kind
        if k is TypeKind.BOOL:
            return np.dtype(np.bool_)
        if k is TypeKind.INT8:
            return np.dtype(np.int8)
        if k is TypeKind.INT16:
            return np.dtype(np.int16)
        if k in (TypeKind.INT32, TypeKind.DATE, TypeKind.VARCHAR):
            return np.dtype(np.int32)
        if k in (TypeKind.INT64, TypeKind.TIMESTAMP):
            return np.dtype(np.int64)
        if k in (TypeKind.FLOAT32, TypeKind.VECTOR):
            return np.dtype(np.float32)
        if k is TypeKind.FLOAT64:
            return np.dtype(np.float64)
        if k is TypeKind.DECIMAL:
            # 9 decimal digits fit int32; wider needs int64.
            return np.dtype(np.int32) if self.precision <= 9 else np.dtype(np.int64)
        raise AssertionError(k)

    @property
    def is_integer(self) -> bool:
        return self.kind in _INT_KINDS

    @property
    def is_numeric(self) -> bool:
        return self.kind in _INT_KINDS or self.kind in (
            TypeKind.FLOAT32,
            TypeKind.FLOAT64,
            TypeKind.DECIMAL,
        )

    @property
    def is_float(self) -> bool:
        return self.kind in (TypeKind.FLOAT32, TypeKind.FLOAT64)

    @property
    def is_decimal(self) -> bool:
        return self.kind is TypeKind.DECIMAL

    @property
    def decimal_factor(self) -> int:
        """10**scale for DECIMAL, 1 otherwise."""
        return 10**self.scale if self.kind is TypeKind.DECIMAL else 1

    def with_nullable(self, nullable: bool) -> "DataType":
        return DataType(self.kind, self.precision, self.scale, nullable)

    def __str__(self) -> str:
        if self.kind is TypeKind.DECIMAL:
            s = f"decimal({self.precision},{self.scale})"
        else:
            s = self.kind.value
        return s + ("?" if self.nullable else "")


# Common singletons
BOOL = DataType.bool_()
INT8 = DataType.int8()
INT16 = DataType.int16()
INT32 = DataType.int32()
INT64 = DataType.int64()
FLOAT32 = DataType.float32()
FLOAT64 = DataType.float64()
DATE = DataType.date()
TIMESTAMP = DataType.timestamp()
VARCHAR = DataType.varchar()


def common_numeric_type(a: DataType, b: DataType) -> DataType:
    """Result type for arithmetic between two numeric types.

    Mirrors (in spirit) the reference's implicit-cast lattice
    (sql/engine/expr/ob_expr_operator.*): float dominates decimal dominates
    integer; integer widths promote to the wider side; decimal arithmetic
    result scales are handled by the expression compiler (see expr/compile.py),
    this only merges storage class.
    """
    if a.is_float or b.is_float:
        k = (
            TypeKind.FLOAT64
            if TypeKind.FLOAT64 in (a.kind, b.kind)
            else TypeKind.FLOAT32
        )
        return DataType(k, nullable=a.nullable or b.nullable)
    if a.is_decimal or b.is_decimal:
        scale = max(a.scale, b.scale)
        prec = max(a.precision - a.scale, b.precision - b.scale) + scale
        return DataType.decimal(min(prec, 18), scale, a.nullable or b.nullable)
    order = [TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.INT64]
    if a.is_integer and b.is_integer:
        k = order[max(order.index(a.kind), order.index(b.kind))]
        return DataType(k, nullable=a.nullable or b.nullable)
    raise TypeError(f"no common numeric type for {a} and {b}")


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType


@dataclass(frozen=True)
class Schema:
    """Ordered, named fields. The analog of a resolved output row type."""

    fields: tuple[Field, ...] = field(default_factory=tuple)

    @staticmethod
    def of(**cols: DataType) -> "Schema":
        return Schema(tuple(Field(n, t) for n, t in cols.items()))

    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def __getitem__(self, name: str) -> DataType:
        for f in self.fields:
            if f.name == name:
                return f.dtype
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def __len__(self) -> int:
        return len(self.fields)

    def index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)
