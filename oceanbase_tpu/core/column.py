"""Column batches: the device-resident unit of execution.

This is the TPU redesign of OceanBase's expression frames + rich vector
formats + ObBatchRows:

- reference frames hold per-expr ObDatum[batch_size] + VectorHeader
  (sql/engine/expr/ob_expr.h:541, code_generator/ob_static_engine_expr_cg.h:70);
  here a batch is a dict of SoA device arrays, one per column.
- reference VectorFormat {FIXED, DISCRETE, CONTINUOUS, UNIFORM, UNIFORM_CONST}
  (share/vector/type_traits.h:23) collapses to: FIXED = dense array,
  DISCRETE/CONTINUOUS (varlen) = dictionary codes (core/dictionary.py),
  UNIFORM_CONST = jnp scalar broadcast (XLA folds it).
- reference ObBatchRows {skip_ bitmap, size_, all_rows_active_}
  (sql/engine/ob_batch_rows.h:26) becomes `sel` (bool mask, True = row live)
  plus `nrows` (live-row count). Capacities are static for XLA; dead tail
  rows are simply masked out, which the VPU handles at full width anyway.

ColumnBatch is a pytree so whole batches flow through jit/shard_map.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import jax
import jax.numpy as jnp
import numpy as np

from .dictionary import Dictionary
from .dtypes import DataType, Field, Schema, TypeKind


@jax.tree_util.register_dataclass
@dataclass
class ColumnBatch:
    """A batch of rows as SoA device arrays, with a live-row mask.

    cols:  name -> values array, shape [capacity], dtype = DataType.storage_np
    valid: name -> bool array (True = non-null); absent for non-nullable cols
    sel:   bool [capacity] live-row mask (ObBatchRows.skip_ inverted)
    nrows: traced scalar count of live rows
    schema: static metadata (field names, logical types)
    dicts: static host-side dictionaries for VARCHAR columns
    """

    cols: dict[str, jnp.ndarray]
    valid: dict[str, jnp.ndarray]
    sel: jnp.ndarray
    nrows: jnp.ndarray
    schema: Schema = field(metadata=dict(static=True), default=Schema())
    dicts: dict[str, Dictionary] = field(
        metadata=dict(static=True), default_factory=dict
    )

    @property
    def capacity(self) -> int:
        return int(self.sel.shape[0])

    def col(self, name: str) -> jnp.ndarray:
        return self.cols[name]

    def validity(self, name: str) -> jnp.ndarray:
        """Validity mask for a column (all-True if non-nullable)."""
        v = self.valid.get(name)
        if v is None:
            return jnp.ones(self.capacity, dtype=jnp.bool_)
        return v

    def with_sel(self, sel: jnp.ndarray) -> "ColumnBatch":
        return replace(self, sel=sel, nrows=jnp.sum(sel, dtype=jnp.int64))

    def project(self, names: list[str]) -> "ColumnBatch":
        fields = tuple(Field(n, self.schema[n]) for n in names)
        return replace(
            self,
            cols={n: self.cols[n] for n in names},
            valid={n: v for n, v in self.valid.items() if n in names},
            schema=Schema(fields),
            dicts={n: d for n, d in self.dicts.items() if n in names},
        )


def batch_rows_storage(batch, names) -> dict:
    """Live rows of a device batch in STORAGE domain (no decimal/date
    decoding — callers materializing Tables need exact round-trips)."""
    sel = np.asarray(batch.sel)
    return {n: np.ascontiguousarray(np.asarray(batch.cols[n])[sel])
            for n in names}


def batch_valid_storage(batch, names) -> dict:
    """Live-row validity masks (only for columns that HAVE one) — the
    NULL half of an exact materialization; dropping it would turn NULLs
    into storage sentinel values."""
    sel = np.asarray(batch.sel)
    return {
        n: np.ascontiguousarray(np.asarray(batch.valid[n])[sel])
        for n in names if n in batch.valid
    }


def renamed_storage_schema(schema_src, names) -> "Schema":
    """Schema of a materialized result: output names zipped positionally
    onto the planned output schema's field types."""
    return Schema(tuple(
        Field(n, schema_src[sn])
        for n, sn in zip(names, schema_src.names())
    ))


def narrow_tier(amin: int, amax: int, itemsize: int):
    """Smallest unsigned dtype that holds [0, amax - amin], if narrower
    than the storage width (the shared frame-of-reference tier rule for
    wire-narrowed uploads)."""
    span = amax - amin
    for nt in (np.uint8, np.uint16, np.uint32):
        if span <= np.iinfo(nt).max and np.dtype(nt).itemsize < itemsize:
            return np.dtype(nt)
    return None


def narrowed_upload(a: np.ndarray, cap: int | None = None):
    """Host->device transfer with the wire cost of the VALUE RANGE, not
    the storage width: integer columns ship frame-of-reference narrowed
    (a - min, downcast to the smallest unsigned dtype that fits the
    span) and decode on device with one cast + one add.

    The network-attached chip moves ~12-30 MB/s host->device (measured
    r4), so wire bytes bound both first-touch table residency and every
    out-of-core streamed chunk; TPC-H's int64-stored decimals/dates
    narrow 2-8x. The device-side cache still holds the full-width
    column — this is a transport encoding, the device-resident analog
    of the reference's FOR-encoded micro-blocks decoded by SIMD readers
    (blocksstable/encoding/ob_dict_decoder_simd.cpp)."""
    def pad(arr, fill=0):
        if cap is None or cap <= len(arr):
            return arr
        return np.concatenate([
            arr,
            np.full((cap - len(arr),) + arr.shape[1:], fill,
                    dtype=arr.dtype),
        ])

    if a.dtype.kind not in "iu" or a.ndim != 1 or len(a) == 0:
        return jnp.asarray(pad(a))
    # frame from the UNPADDED values: zero-padding an all-positive column
    # (dates, keys, scaled decimals) would drag the frame base to 0 and
    # forfeit most of the narrowing; dead pad rows carry amin instead
    amin = int(a.min())
    nt = narrow_tier(amin, int(a.max()), a.dtype.itemsize)
    if nt is None:
        return jnp.asarray(pad(a))
    narrow = pad((a - amin).astype(nt))
    return (jnp.asarray(narrow).astype(a.dtype)
            + np.asarray(amin, dtype=a.dtype))


def make_batch(
    data: dict[str, np.ndarray],
    schema: Schema,
    dicts: dict[str, Dictionary] | None = None,
    capacity: int | None = None,
    valid: dict[str, np.ndarray] | None = None,
) -> ColumnBatch:
    """Build a ColumnBatch from host arrays, padding to `capacity`.

    Capacity defaults to nrows rounded up to a multiple of 1024 (keeps XLA
    tiling happy: last-dim lanes of 128, sublane multiples).
    """
    names = schema.names()
    n = len(next(iter(data.values()))) if data else 0
    for name in names:
        if len(data[name]) != n:
            raise ValueError(f"column {name} length mismatch")
    cap = capacity if capacity is not None else max(1024, -(-n // 1024) * 1024)
    if cap < n:
        raise ValueError(f"capacity {cap} < nrows {n}")

    cols: dict[str, jnp.ndarray] = {}
    vmap_: dict[str, jnp.ndarray] = {}
    for f in schema.fields:
        a = np.asarray(data[f.name], dtype=f.dtype.storage_np)
        cols[f.name] = narrowed_upload(a, cap)
        if f.dtype.nullable:
            v = (
                np.asarray(valid[f.name], dtype=np.bool_)
                if valid and f.name in valid
                else np.ones(n, dtype=np.bool_)
            )
            if cap > n:
                v = np.concatenate([v, np.zeros(cap - n, dtype=np.bool_)])
            vmap_[f.name] = jnp.asarray(v)
    sel = np.zeros(cap, dtype=np.bool_)
    sel[:n] = True
    return ColumnBatch(
        cols=cols,
        valid=vmap_,
        sel=jnp.asarray(sel),
        nrows=jnp.asarray(n, dtype=jnp.int64),
        schema=schema,
        dicts=dict(dicts or {}),
    )


def batch_rows_normalized(
    batch: ColumnBatch, names, ndigits: int = 4
) -> list[tuple]:
    """Result rows as a sorted list of comparable tuples: floats rounded,
    NaN -> None, numpy scalars unboxed. The canonical form for comparing
    two executions of the same plan (distributed vs single-chip checks,
    oracle comparisons)."""
    host = batch_to_host(batch)
    n = len(next(iter(host.values()))) if host else 0
    out = []
    for i in range(n):
        row = []
        for nm in names:
            v = host[nm][i]
            if isinstance(v, (float, np.floating)):
                v = None if np.isnan(v) else round(float(v), ndigits)
            elif isinstance(v, np.integer):
                v = int(v)
            row.append(v)
        out.append(tuple(row))
    return sorted(out, key=lambda r: tuple((x is None, str(x)) for x in r))


def batch_to_host(batch: ColumnBatch, decode_strings: bool = True) -> dict[str, np.ndarray | list]:
    """Pull live rows back to host (compacting out dead rows).

    NULL rows of nullable columns surface as None (lists) / NaN (floats) /
    masked ints via an object-dtype fallback, so callers never see the
    garbage payloads stored under invalid slots.
    """
    sel = np.asarray(batch.sel)
    cols = {f.name: np.asarray(batch.cols[f.name]) for f in batch.schema.fields}
    valid = {n: np.asarray(v) for n, v in batch.valid.items()}
    return host_rows(
        batch.schema, batch.dicts, cols, valid, sel,
        decode_strings=decode_strings,
    )


def host_rows(schema, dicts, hcols, hvalid, hsel,
              decode_strings: bool = True) -> dict[str, np.ndarray | list]:
    """batch_to_host over ALREADY-FETCHED numpy arrays (the single-
    device_get dispatch path, engine/executor.py run_host)."""
    out: dict[str, np.ndarray | list] = {}
    for f in schema.fields:
        a = np.asarray(hcols[f.name])[hsel]
        v = hvalid.get(f.name)
        vm = np.asarray(v)[hsel] if v is not None else None
        if f.dtype.kind is TypeKind.VARCHAR and decode_strings and f.name in dicts:
            codes = a.copy()
            if vm is not None:
                codes[~vm] = -1  # Dictionary.decode maps negatives to None
            out[f.name] = dicts[f.name].decode(codes)
        elif f.dtype.is_decimal:
            d = a.astype(np.float64) / f.dtype.decimal_factor
            if vm is not None:
                d[~vm] = np.nan
            out[f.name] = d
        elif vm is not None and not vm.all():
            o = a.astype(object)
            o[~vm] = None
            out[f.name] = o
        else:
            out[f.name] = a
    return out


def host_rows_batched(schema, dicts, hcols, hvalid, hsel,
                      decode_strings: bool = True) -> list[dict]:
    """host_rows over a whole statement micro-batch at once.

    `hcols`/`hvalid` values carry a leading [B] lane axis and `hsel` is
    [B, cap]; returns one column dict per lane. One flatten + offset
    slicing per column replaces B per-lane boolean gathers, so the
    batcher's scatter cost stops scaling with lane count. (Lanes share
    one flat decode, so a NULL in any lane switches a nullable column's
    dtype fallback for all lanes of this batch — the surfaced values are
    identical either way.)"""
    nb = int(hsel.shape[0])
    counts = hsel.sum(axis=1)
    offs = np.zeros(nb + 1, dtype=np.int64)
    np.cumsum(counts, out=offs[1:])
    flat: dict[str, np.ndarray | list] = {}
    for f in schema.fields:
        a = np.asarray(hcols[f.name])[hsel]
        v = hvalid.get(f.name)
        vm = np.asarray(v)[hsel] if v is not None else None
        if f.dtype.kind is TypeKind.VARCHAR and decode_strings and f.name in dicts:
            codes = a.copy()
            if vm is not None:
                codes[~vm] = -1
            flat[f.name] = dicts[f.name].decode(codes)
        elif f.dtype.is_decimal:
            d = a.astype(np.float64) / f.dtype.decimal_factor
            if vm is not None:
                d[~vm] = np.nan
            flat[f.name] = d
        elif vm is not None and not vm.all():
            o = a.astype(object)
            o[~vm] = None
            flat[f.name] = o
        else:
            flat[f.name] = a
    return [
        {n: c[offs[i]:offs[i + 1]] for n, c in flat.items()}
        for i in range(nb)
    ]
