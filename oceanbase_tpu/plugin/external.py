"""External-table loaders: Arrow/Parquet/CSV into catalog Tables.

Reference: src/plugin/.../ob_external_arrow_data_loader.h (the external
Arrow loader behind OceanBase's external tables) and the external-table
scan layer under src/sql/engine — there the loader feeds scan batches;
here it feeds a columnar Table whose arrays upload once to HBM, after
which external data is indistinguishable from native tables (all the
engine's fast paths — affine joins, sorted projections over it, stats —
apply).

Type mapping (Arrow -> engine storage):
  int8/16/32/64, uint*  -> matching signed ints (uint64 -> int64)
  float32/float64       -> float32/float64
  date32                -> DATE (int32 days)
  decimal128(p, s)      -> DECIMAL(p, s) scaled int
  string/large_string   -> dict-encoded VARCHAR
  bool                  -> BOOL
Nullable arrow columns carry their validity into the Table's masks.
"""

from __future__ import annotations

import numpy as np

from ..core.dictionary import Dictionary
from ..core.dtypes import DataType, Field, Schema, TypeKind
from ..core.table import Table


class ExternalFormatError(Exception):
    pass


_LOADERS = {}


def register_loader(fmt: str, fn) -> None:
    """fn(path) -> pyarrow.Table-like or (data, dicts, schema) triple."""
    _LOADERS[fmt.lower()] = fn


def registered_formats() -> tuple[str, ...]:
    return tuple(sorted(_LOADERS))


# ---------------------------------------------------------------- arrow

def _arrow_to_table(name: str, at) -> Table:
    import pyarrow as pa

    data: dict[str, np.ndarray] = {}
    dicts: dict[str, Dictionary] = {}
    valid: dict[str, np.ndarray] = {}
    fields = []
    for col in at.schema.names:
        arr = at.column(col).combine_chunks()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        t = arr.type
        nullable = arr.null_count > 0
        if pa.types.is_boolean(t):
            dt = DataType.bool_(nullable)
            np_arr = arr.to_numpy(zero_copy_only=False)
            data[col] = np.asarray(np_arr, dtype=np.bool_)
        elif pa.types.is_integer(t):
            dt = (
                DataType.int64(nullable)
                if t.bit_width > 32 or pa.types.is_unsigned_integer(t)
                else DataType(TypeKind.INT32, nullable=nullable)
                if t.bit_width > 16
                else DataType(TypeKind.INT16, nullable=nullable)
                if t.bit_width > 8
                else DataType(TypeKind.INT8, nullable=nullable)
            )
            raw = arr.fill_null(0).to_numpy(zero_copy_only=False)
            if raw.dtype == np.uint64 and len(raw) and (
                raw.max() > np.iinfo(np.int64).max
            ):
                # silent wraparound to negatives would corrupt results
                raise ExternalFormatError(
                    f"uint64 column {col} holds values beyond int64 "
                    "(the engine has no unsigned 64-bit storage)"
                )
            data[col] = np.asarray(raw, dtype=dt.storage_np)
        elif pa.types.is_floating(t):
            dt = (
                DataType.float32(nullable) if t.bit_width == 32
                else DataType.float64(nullable)
            )
            data[col] = np.asarray(
                arr.fill_null(0.0).to_numpy(zero_copy_only=False),
                dtype=dt.storage_np,
            )
        elif pa.types.is_date32(t):
            dt = DataType(TypeKind.DATE, nullable=nullable)
            data[col] = np.asarray(
                arr.fill_null(0).cast(pa.int32()).to_numpy(
                    zero_copy_only=False),
                dtype=np.int32,
            )
        elif pa.types.is_decimal(t):
            dt = DataType.decimal(t.precision, t.scale, nullable)
            # decimal.Decimal scaleb keeps exactness: value * 10^scale
            data[col] = np.asarray(
                [int(v.scaleb(t.scale)) if v is not None else 0
                 for v in arr.fill_null(0).to_pylist()],
                dtype=dt.storage_np,
            )
        elif pa.types.is_string(t) or pa.types.is_large_string(t):
            dt = DataType.varchar(nullable)
            py = arr.fill_null("").to_pylist()
            d = Dictionary(sorted(set(py)), sorted_=True)
            data[col] = d.encode(py, add=False)
            dicts[col] = d
        else:
            raise ExternalFormatError(
                f"unsupported arrow type {t} for column {col}"
            )
        if nullable:
            valid[col] = np.asarray(
                arr.is_valid().to_numpy(zero_copy_only=False),
                dtype=np.bool_,
            )
        fields.append(Field(col, dt))
    return Table(name, Schema(tuple(fields)), data, dicts, valid)


def _load_parquet(path: str):
    import pyarrow.parquet as pq

    return pq.read_table(path)


def _load_arrow(path: str):
    import pyarrow as pa

    with pa.memory_map(path) as src:
        return pa.ipc.open_file(src).read_all()


def _load_csv(path: str):
    import pyarrow.csv as pacsv

    return pacsv.read_csv(path)


register_loader("parquet", _load_parquet)
register_loader("arrow", _load_arrow)
register_loader("csv", _load_csv)


def load_external(name: str, fmt: str, path: str) -> Table:
    """Materialize an external file as a catalog Table."""
    fn = _LOADERS.get(fmt.lower())
    if fn is None:
        raise ExternalFormatError(
            f"no loader for format {fmt!r} (have {registered_formats()})"
        )
    out = fn(path)
    if isinstance(out, Table):
        return out
    if isinstance(out, tuple):
        data, dicts, schema = out
        return Table(name, schema, data, dicts or {})
    return _arrow_to_table(name, out)
