"""Plugin surface: external data loaders.

Reference surface: src/plugin — OceanBase's plugin framework whose
north-star-named member is the external Arrow data loader
(ob_external_arrow_data_loader.h): external tables declare a format +
location, and a registered loader materializes batches at scan time.

The rebuild keeps the same two pieces at this engine's scale:
- a LOADER REGISTRY keyed by format name (arrow/parquet/csv built in,
  user-registered loaders join the same dict), and
- CREATE EXTERNAL TABLE ... USING <format> LOCATION '<path>' DDL that
  routes through it into a catalog Table (columnar from the first byte:
  an Arrow column IS the device column after one dtype mapping).
"""

from .external import (  # noqa: F401
    ExternalFormatError,
    load_external,
    register_loader,
    registered_formats,
)
