from .hashing import (
    fold32,
    hash32_combine,
    hash_combine,
    mix32,
    mix64,
    next_pow2,
    pack_keys,
)
from .hashagg import (
    assign_group_slots,
    groupby_direct,
    groupby_hash,
    scalar_aggregate,
)
from .join import (
    build_hash_table,
    expand_join,
    gather_payload,
    hash_join_probe,
    join_keys64,
    sort_build_side,
)
from .sort import apply_order, sort_indices, topn_indices

__all__ = [
    "fold32",
    "hash32_combine",
    "mix32",
    "hash_combine",
    "mix64",
    "next_pow2",
    "pack_keys",
    "assign_group_slots",
    "groupby_direct",
    "groupby_hash",
    "scalar_aggregate",
    "build_hash_table",
    "expand_join",
    "gather_payload",
    "hash_join_probe",
    "join_keys64",
    "sort_build_side",
    "apply_order",
    "sort_indices",
    "topn_indices",
]
