"""Sort / top-n kernels.

Reference surface: ObSortVecOp with adaptive quicksort + external merge
(sql/engine/sort/ob_sort_adaptive_qs_vec_op.h) and top-n pushdown
(ob_pd_topn_sort_filter.h). On TPU the whole batch sorts in one fused XLA
`lax.sort` (bitonic-style network on device) — no spill tier is needed until
a partition exceeds HBM, which the parallel layer avoids by range/hash
repartitioning first (the reference's own strategy, just static).

Multi-key ORDER BY maps to `lax.sort` with num_keys = k + 1: a leading
liveness key forces masked-out rows to the tail, then the user keys in
order. DESC keys are value-negated (ints/floats) — exact for every physical
type we store because decimals/dates/dict-codes are ints well inside the
int64 range.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_LO_FLIP = jnp.int32(-2147483648)  # top-bit xor: unsigned order as signed


def _desc_transform(v: jnp.ndarray) -> jnp.ndarray:
    if v.dtype == jnp.bool_:
        return ~v
    return -v


def split_sort_key(v: jnp.ndarray, descending: bool = False
                   ) -> list[jnp.ndarray]:
    """Order-preserving int32 planes of one sort key.

    Measured v5e cliff: `lax.sort` with MORE THAN ONE int64 operand goes
    superlinear past ~16M rows (32M: 196ms with one i64 key + i32 values
    vs ~6s with a second i64 operand). Splitting every int64 key into
    (hi32 signed, lo32 bit-flipped) preserves lexicographic order exactly
    — hi compares signed like the original, lo's unsigned order maps onto
    signed int32 by flipping the top bit."""
    if v.dtype == jnp.bool_:
        return [(~v if descending else v).astype(jnp.int32)]
    if v.dtype == jnp.int64:
        x = -v if descending else v
        hi = (x >> 32).astype(jnp.int32)
        lo = x.astype(jnp.int32) ^ _LO_FLIP
        return [hi, lo]
    return [_desc_transform(v) if descending else v]


def rebuild_i64(hi: jnp.ndarray, lo: jnp.ndarray) -> jnp.ndarray:
    """Inverse of split_sort_key's int64 split (ascending form)."""
    u = (lo ^ _LO_FLIP).astype(jnp.uint32).astype(jnp.int64)
    return (hi.astype(jnp.int64) << 32) | u


def sort_indices(
    keys: list[jnp.ndarray], descending: list[bool], mask: jnp.ndarray
) -> jnp.ndarray:
    """Return row order (int32 [N]) sorting live rows by keys; dead rows last.

    Stable across equal keys (ties keep original order) because the original
    row index is appended as the final key. int64 keys ride the two-plane
    split (see split_sort_key).
    """
    n = mask.shape[0]
    ops = [(~mask)]  # dead rows (True) sort after live (False)
    for k, d in zip(keys, descending):
        ops.extend(split_sort_key(k, d))
    idx = jnp.arange(n, dtype=jnp.int32)
    ops.append(idx)
    out = jax.lax.sort(tuple(ops), num_keys=len(ops))
    return out[-1]


def apply_order(columns: dict[str, jnp.ndarray], order: jnp.ndarray):
    return {name: c[order] for name, c in columns.items()}


def topn_indices(
    keys: list[jnp.ndarray],
    descending: list[bool],
    mask: jnp.ndarray,
    n_top: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-n rows by sort keys. Returns (order [n_top], valid [n_top]).

    Full sort then slice: XLA's sort is fast enough that a separate heap
    path only pays off for tiny n over huge batches; revisit with a pallas
    partial-sort if profiling says so.
    """
    order = sort_indices(keys, descending, mask)
    top = order[:n_top]
    nlive = jnp.sum(mask, dtype=jnp.int64)
    valid = jnp.arange(n_top, dtype=jnp.int64) < nlive
    return top, valid
