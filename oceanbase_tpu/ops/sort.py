"""Sort / top-n kernels.

Reference surface: ObSortVecOp with adaptive quicksort + external merge
(sql/engine/sort/ob_sort_adaptive_qs_vec_op.h) and top-n pushdown
(ob_pd_topn_sort_filter.h). On TPU the whole batch sorts in one fused XLA
`lax.sort` (bitonic-style network on device) — no spill tier is needed until
a partition exceeds HBM, which the parallel layer avoids by range/hash
repartitioning first (the reference's own strategy, just static).

Multi-key ORDER BY maps to `lax.sort` with num_keys = k + 1: a leading
liveness key forces masked-out rows to the tail, then the user keys in
order. DESC keys are value-negated (ints/floats) — exact for every physical
type we store because decimals/dates/dict-codes are ints well inside the
int64 range.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _desc_transform(v: jnp.ndarray) -> jnp.ndarray:
    if v.dtype == jnp.bool_:
        return ~v
    return -v


def sort_indices(
    keys: list[jnp.ndarray], descending: list[bool], mask: jnp.ndarray
) -> jnp.ndarray:
    """Return row order (int32 [N]) sorting live rows by keys; dead rows last.

    Stable across equal keys (ties keep original order) because the original
    row index is appended as the final key.
    """
    n = mask.shape[0]
    ops = [(~mask)]  # dead rows (True) sort after live (False)
    for k, d in zip(keys, descending):
        ops.append(_desc_transform(k) if d else k)
    idx = jnp.arange(n, dtype=jnp.int32)
    ops.append(idx)
    out = jax.lax.sort(tuple(ops), num_keys=len(ops))
    return out[-1]


def apply_order(columns: dict[str, jnp.ndarray], order: jnp.ndarray):
    return {name: c[order] for name, c in columns.items()}


def topn_indices(
    keys: list[jnp.ndarray],
    descending: list[bool],
    mask: jnp.ndarray,
    n_top: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-n rows by sort keys. Returns (order [n_top], valid [n_top]).

    Full sort then slice: XLA's sort is fast enough that a separate heap
    path only pays off for tiny n over huge batches; revisit with a pallas
    partial-sort if profiling says so.
    """
    order = sort_indices(keys, descending, mask)
    top = order[:n_top]
    nlive = jnp.sum(mask, dtype=jnp.int64)
    valid = jnp.arange(n_top, dtype=jnp.int64) < nlive
    return top, valid
