"""HyperLogLog NDV sketch — scatter-free, fixed-memory, mergeable.

Reference surface: src/share/aggregate/approx_count_distinct.cpp (the
ObAggregateProcessor HLL with 2^14 buckets). The rebuild keeps the same
register geometry (m = 2^14, alpha = 0.7213/(1+1.079/m), linear-counting
small-range correction) but computes the register array WITHOUT scatters
— the measured TPU cliff that shaped every kernel in ops/ (see
ops/hashagg.py):

  * bucket index and rank come from two INDEPENDENT 32-bit mixes of the
    value (murmur3 fmix32 with different seeds), giving an effective
    46-bit hash space — no large-range correction needed at any NDV the
    device can hold;
  * (bucket << 6 | rank) packs into one int32 sort key; after ONE sort,
    the maximum rank of every touched bucket is the last element of its
    run;
  * the dense [m] register array materializes by a searchsorted + gather
    over the sorted keys (m lookups, no scatter).

The register array is the mergeable form (elementwise max), sized 16K
int32 — constant memory regardless of input NDV, which is the entire
point of the operator: the exact distinct-count path (first-occurrence
masks) needs the full value set resident, this needs 64KB.
"""

from __future__ import annotations

import jax.numpy as jnp

from .hashing import _GOLDEN32, fold32, mix32

M_LOG2 = 14
M = 1 << M_LOG2  # 16384 registers, matching the reference's bucket count
_RANK_BITS = 6  # ranks are 1..33; 6 bits


def _two_hashes(col: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Two independent 32-bit avalanche hashes of a key column.

    Float columns are BITCAST to same-width ints before folding: fold32's
    value-cast would truncate 0.1..0.9 all to 0, and unlike every other
    fold32 consumer (joins/blooms re-check real keys) a sketch has no
    equality recheck to absorb the collision."""
    if jnp.issubdtype(col.dtype, jnp.floating):
        import jax

        wide = col.astype(jnp.float64)
        col = jax.lax.bitcast_convert_type(wide, jnp.int64)
    f = fold32(col)
    h1 = mix32(f + _GOLDEN32)
    h2 = mix32(h1 ^ f ^ jnp.uint32(0x85EBCA6B))
    return h1, h2


def hll_registers(col: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """[N] values + live mask -> [M] int32 HLL register array."""
    h1, h2 = _two_hashes(col)
    bucket = (h1 & jnp.uint32(M - 1)).astype(jnp.int32)
    # rank = leading zeros of h2 (as a 32-bit word) + 1; h2 == 0 -> 33.
    # floor(log2) via float64 is exact for values < 2^32 (52-bit mantissa).
    h2f = h2.astype(jnp.float64)
    rank = jnp.where(
        h2 == 0,
        jnp.int32(33),
        (jnp.int32(32) - jnp.floor(jnp.log2(jnp.maximum(h2f, 1.0))).astype(jnp.int32)),
    )
    packed = jnp.where(
        mask, (bucket << _RANK_BITS) | rank, jnp.int32(-1)
    )
    sp = jnp.sort(packed)  # dead rows (-1) sort first
    # register j = rank part of the largest packed value in j's bucket
    buckets = jnp.arange(M, dtype=jnp.int32)
    pos = jnp.searchsorted(sp, (buckets + 1) << _RANK_BITS, side="left") - 1
    v = sp[jnp.clip(pos, 0, None)]
    hit = (pos >= 0) & (v >= (buckets << _RANK_BITS)) & (v >= 0)
    return jnp.where(hit, v & ((1 << _RANK_BITS) - 1), 0).astype(jnp.int32)


def hll_estimate(regs: jnp.ndarray) -> jnp.ndarray:
    """Register array -> int64 cardinality estimate (standard corrections:
    linear counting below 2.5m with empty registers present)."""
    m = regs.shape[0]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv = jnp.sum(jnp.exp2(-regs.astype(jnp.float64)))
    raw = alpha * m * m / inv
    zeros = jnp.sum(regs == 0)
    small = m * jnp.log(m / jnp.maximum(zeros, 1).astype(jnp.float64))
    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), small, raw)
    return jnp.round(est).astype(jnp.int64)


def hll_count(col: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """One-shot approx NDV of a masked column (scalar-aggregate path)."""
    return hll_estimate(hll_registers(col, mask))


def hll_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Union of two sketches = elementwise register max — the merge form
    for OVERLAPPING inputs (out-of-core chunk streaming, where chunk value
    sets intersect). PX does NOT use this: it hash-colocates rows by the
    argument first, so shards sketch DISJOINT sets and the int64 estimates
    simply psum (parallel/px.py)."""
    return jnp.maximum(a, b)
