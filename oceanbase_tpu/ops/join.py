"""Vectorized join kernels.

Reference surface: ObHashJoinVecOp (sql/engine/join/hash_join/
ob_hash_join_vec_op.h:316 — build :402, probe :425), merge join, and
nested-loop join. The TPU redesign avoids pointer-chasing buckets entirely:

- hash_join_probe (unique build keys — the PK-FK case that covers most
  TPC-H/TPC-DS joins): build side inserts into an open-addressing table via
  the same lockstep-probe scatter loop as group-by; probe rows then walk the
  probe chain in lockstep gathers until they hit their key or an empty slot.
  Output keeps the probe side's static capacity: each probe row gets the
  matching build row index (or -1), and payload columns materialize by
  gather. Inner/semi/anti/left-outer all fall out of the match mask.

- expand_join (M:N general case): sort the build side by key once, binary
  search each probe key's [lo, hi) duplicate range, prefix-sum the counts,
  and scatter/gather-expand into a static output capacity. The engine
  chooses capacity from optimizer cardinality estimates and re-executes
  with a larger capacity on overflow (detected via the returned total).

Both paths are pure jittable functions with static shapes; XLA fuses the
surrounding filters/projections into the gathers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .hashagg import assign_group_slots
from .hashing import hash_combine, next_pow2

_I64_MIN = jnp.iinfo(jnp.int64).min


def join_keys64(key_cols: list[jnp.ndarray]) -> jnp.ndarray:
    """Canonical 64-bit join key. Single integer key columns pass through
    exactly (no collision risk); multi-column keys hash-combine (the engine
    routes multi-key M:N joins through an extra exact post-filter on the
    expanded pairs, so a 2^-64 collision cannot fabricate a result row)."""
    if len(key_cols) == 1 and jnp.issubdtype(key_cols[0].dtype, jnp.integer):
        return key_cols[0].astype(jnp.int64)
    return hash_combine(key_cols).astype(jnp.int64)


def build_hash_table(
    key_cols: list[jnp.ndarray], mask: jnp.ndarray, table_size: int
):
    """Insert build rows into an open-addressing table.

    Unique keys assumed (duplicates: one winner per key survives — callers
    needing M:N semantics use expand_join). Returns (slot_tag [T] int32
    32-bit hash tags, slot_row [T] int32; empty slots have slot_row < 0).
    """
    from .hashing import hash32_combine

    row_slot, slot_used, slot_row = assign_group_slots(key_cols, mask, table_size)
    tags = hash32_combine(key_cols).astype(jnp.int32)
    n = key_cols[0].shape[0]
    slot_tag = jnp.where(slot_used, tags[jnp.clip(slot_row, 0, n - 1)], 0)
    return slot_tag, slot_row


def hash_join_probe(
    slot_tag: jnp.ndarray,
    slot_row: jnp.ndarray,
    build_key_cols: list[jnp.ndarray],
    probe_key_cols: list[jnp.ndarray],
    probe_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Probe the table; returns match_row [N] int32 (build row idx or -1).

    A hit requires tag equality AND exact equality of every key column, so
    32-bit tag collisions cost an extra probe step, never a wrong match."""
    from .hashing import hash32_combine, inherit_vma

    ts = slot_tag.shape[0]
    nb = build_key_cols[0].shape[0]
    n = probe_key_cols[0].shape[0]
    tags = hash32_combine(probe_key_cols).astype(jnp.int32)
    h = (tags.astype(jnp.uint32) & jnp.uint32(ts - 1)).astype(jnp.int32)

    def cond(state):
        pending, probe, _ = state
        return jnp.logical_and(jnp.any(pending), probe < ts)

    def body(state):
        pending, probe, match_row = state
        pos = ((h + probe) & (ts - 1)).astype(jnp.int32)
        at_row_raw = slot_row[pos]
        empty = at_row_raw < 0
        at_row = jnp.clip(at_row_raw, 0, nb - 1)
        exact = jnp.ones(n, dtype=jnp.bool_)
        for bc, pc in zip(build_key_cols, probe_key_cols):
            exact = exact & (bc[at_row] == pc)
        hit = pending & ~empty & (slot_tag[pos] == tags) & exact
        match_row = jnp.where(hit, at_row_raw, match_row)
        pending = pending & ~hit & ~empty
        return pending, probe + 1, match_row

    init = (
        probe_mask,
        inherit_vma(jnp.zeros((), jnp.int32), tags),
        inherit_vma(jnp.full(n, -1, jnp.int32), tags),
    )
    _, _, match_row = jax.lax.while_loop(cond, body, init)
    return match_row


def gather_payload(
    columns: dict[str, jnp.ndarray], match_row: jnp.ndarray
) -> dict[str, jnp.ndarray]:
    """Materialize build-side payload columns for matched probe rows."""
    idx = jnp.clip(match_row, 0, None)
    return {name: c[idx] for name, c in columns.items()}


def expand_join(
    build_sorted_keys64: jnp.ndarray,
    build_order: jnp.ndarray,
    build_nrows: jnp.ndarray,
    probe_key_cols: list[jnp.ndarray],
    probe_mask: jnp.ndarray,
    out_capacity: int,
):
    """M:N join expansion against a key-sorted build side.

    build_sorted_keys64: 64-bit mixed keys of build rows, ascending, with
    dead rows sorted to the end (callers pass +inf-like sentinel);
    build_order: original build row index per sorted position;
    Returns (out_probe_row [C] int32, out_build_row [C] int32, out_valid [C]
    bool, total matches [scalar int64]). If total > out_capacity the output
    is truncated — the engine checks and re-runs with a larger capacity.
    """
    keys64 = join_keys64(probe_key_cols)
    lo = jnp.searchsorted(build_sorted_keys64, keys64, side="left")
    hi = jnp.searchsorted(build_sorted_keys64, keys64, side="right")
    cnt = jnp.where(probe_mask, (hi - lo).astype(jnp.int64), 0)
    offs = jnp.cumsum(cnt)  # inclusive prefix sum
    total = offs[-1] if cnt.shape[0] > 0 else jnp.zeros((), jnp.int64)
    starts = offs - cnt  # exclusive
    # for each output slot t: probe row p = first row with offs[p] > t
    t = jnp.arange(out_capacity, dtype=jnp.int64)
    p = jnp.searchsorted(offs, t, side="right").astype(jnp.int32)
    pc = jnp.clip(p, 0, cnt.shape[0] - 1)
    k = t - starts[pc]
    b_sorted_pos = (lo[pc].astype(jnp.int64) + k).astype(jnp.int32)
    out_valid = t < total
    nb = build_order.shape[0]
    out_build_row = build_order[jnp.clip(b_sorted_pos, 0, nb - 1)]
    return pc, out_build_row, out_valid, total


def sort_build_side(key_cols: list[jnp.ndarray], mask: jnp.ndarray):
    """Sort build rows by mixed 64-bit key for expand_join; dead rows last."""
    keys64 = join_keys64(key_cols)
    keys64 = jnp.where(mask, keys64, jnp.iinfo(jnp.int64).max)
    n = keys64.shape[0]
    order = jnp.argsort(keys64)
    return keys64[order], order.astype(jnp.int32)
