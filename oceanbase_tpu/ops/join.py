"""Vectorized join kernels.

Reference surface: ObHashJoinVecOp (sql/engine/join/hash_join/
ob_hash_join_vec_op.h:316 — build :402, probe :425), merge join, and
nested-loop join.

TPU redesign, driven by measured v5e costs (8M rows: sort ~20ms, cumsum
~7ms, random gather ~60-120ms, scatter ~1.1s, open-addressing while-loops
~30s): the hot joins are SORT-based and scatter-free.

- merge_join_unique (unique single-int-key build — the PK-FK case that
  covers most TPC-H/TPC-DS joins): one combined sort of (key, side, row)
  over build++probe; within a key run the build row (if any) sorts first,
  a segmented cummax pins it, and an inverse permutation (argsort of the
  sort permutation — a sort, not a scatter) maps matches back to original
  probe order. Output keeps the probe side's static capacity: each probe
  row gets the matching build row index (or -1), and payload columns
  materialize by gather.

- expand_join (M:N general case): sort the build side by key once, binary
  search each probe key's [lo, hi) duplicate range (searchsorted
  method='sort' — the scan variant costs 20x on TPU), prefix-sum the
  counts, and gather-expand into a static output capacity. The engine
  chooses capacity from optimizer cardinality estimates and re-executes
  with a larger capacity on overflow (detected via the returned total).

- build_hash_table / hash_join_probe (open-addressing lockstep loops) stay
  for cold paths that need multi-column existence probes (set operations);
  they are correct everywhere but orders of magnitude slower on TPU.

All paths are pure jittable functions with static shapes; XLA fuses the
surrounding filters/projections into the gathers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .hashagg import assign_group_slots
from .hashing import hash_combine, next_pow2

_I64_MIN = jnp.iinfo(jnp.int64).min


def join_keys64(key_cols: list[jnp.ndarray]) -> jnp.ndarray:
    """Canonical 64-bit join key. Single integer key columns pass through
    exactly (no collision risk); multi-column keys hash-combine (the engine
    routes multi-key M:N joins through an extra exact post-filter on the
    expanded pairs, so a 2^-64 collision cannot fabricate a result row)."""
    if len(key_cols) == 1 and jnp.issubdtype(key_cols[0].dtype, jnp.integer):
        return key_cols[0].astype(jnp.int64)
    return hash_combine(key_cols).astype(jnp.int64)


def build_hash_table(
    key_cols: list[jnp.ndarray], mask: jnp.ndarray, table_size: int
):
    """Insert build rows into an open-addressing table.

    Unique keys assumed (duplicates: one winner per key survives — callers
    needing M:N semantics use expand_join). Returns (slot_tag [T] int32
    32-bit hash tags, slot_row [T] int32; empty slots have slot_row < 0).
    """
    from .hashing import hash32_combine

    row_slot, slot_used, slot_row = assign_group_slots(key_cols, mask, table_size)
    tags = hash32_combine(key_cols).astype(jnp.int32)
    n = key_cols[0].shape[0]
    slot_tag = jnp.where(slot_used, tags[jnp.clip(slot_row, 0, n - 1)], 0)
    return slot_tag, slot_row


def hash_join_probe(
    slot_tag: jnp.ndarray,
    slot_row: jnp.ndarray,
    build_key_cols: list[jnp.ndarray],
    probe_key_cols: list[jnp.ndarray],
    probe_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Probe the table; returns match_row [N] int32 (build row idx or -1).

    A hit requires tag equality AND exact equality of every key column, so
    32-bit tag collisions cost an extra probe step, never a wrong match."""
    from .hashing import hash32_combine, inherit_vma

    ts = slot_tag.shape[0]
    nb = build_key_cols[0].shape[0]
    n = probe_key_cols[0].shape[0]
    tags = hash32_combine(probe_key_cols).astype(jnp.int32)
    h = (tags.astype(jnp.uint32) & jnp.uint32(ts - 1)).astype(jnp.int32)

    def cond(state):
        pending, probe, _ = state
        return jnp.logical_and(jnp.any(pending), probe < ts)

    def body(state):
        pending, probe, match_row = state
        pos = ((h + probe) & (ts - 1)).astype(jnp.int32)
        at_row_raw = slot_row[pos]
        empty = at_row_raw < 0
        at_row = jnp.clip(at_row_raw, 0, nb - 1)
        exact = jnp.ones(n, dtype=jnp.bool_)
        for bc, pc in zip(build_key_cols, probe_key_cols):
            exact = exact & (bc[at_row] == pc)
        hit = pending & ~empty & (slot_tag[pos] == tags) & exact
        match_row = jnp.where(hit, at_row_raw, match_row)
        pending = pending & ~hit & ~empty
        return pending, probe + 1, match_row

    init = (
        probe_mask,
        inherit_vma(jnp.zeros((), jnp.int32), tags),
        inherit_vma(jnp.full(n, -1, jnp.int32), tags),
    )
    _, _, match_row = jax.lax.while_loop(cond, body, init)
    return match_row


def merge_join_unique(
    build_key: jnp.ndarray,
    build_mask: jnp.ndarray,
    probe_key: jnp.ndarray,
    probe_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Unique-build join on ONE integer key column via a combined sort.

    Returns match_row [Np] int32 in ORIGINAL probe order (-1 = no match).
    Exact (sorts true keys, no hashing). Duplicate build keys: one winner
    per key (the one sorting first), same contract as build_hash_table.

    Deadness rides as a separate LEADING sort operand rather than an
    in-band sentinel value, so the full int64 key domain (including
    2^62.. and int64 max) joins correctly.
    """
    nb = build_key.shape[0]
    npr = probe_key.shape[0]
    n = nb + npr
    keys = jnp.concatenate(
        [build_key.astype(jnp.int64), probe_key.astype(jnp.int64)]
    )
    dead = jnp.concatenate([~build_mask, ~probe_mask]).astype(jnp.int32)
    side = jnp.concatenate(
        [jnp.zeros(nb, jnp.int32), jnp.ones(npr, jnp.int32)]
    )
    idx = jnp.concatenate(
        [jnp.arange(nb, dtype=jnp.int32), jnp.arange(npr, dtype=jnp.int32)]
    )
    sdead, sk, sside, sidx = jax.lax.sort(
        (dead, keys, side, idx), num_keys=3
    )
    pos = jnp.arange(n, dtype=jnp.int32)
    new_run = jnp.concatenate(
        [jnp.ones(1, jnp.bool_),
         (sk[1:] != sk[:-1]) | (sdead[1:] != sdead[:-1])]
    )
    run_start = jax.lax.cummax(jnp.where(new_run, pos, 0))
    b_at_start = sside[run_start] == 0
    cand = sidx[run_start]
    match_sorted = jnp.where(
        (sside == 1) & (sdead == 0) & b_at_start, cand, -1
    )
    # inverse permutation restricted to probe entries — computed by a
    # second sort (argsort), never a scatter
    inv = jnp.argsort(sside.astype(jnp.int64) * n + sidx)
    return match_sorted[inv[nb:]]


def gather_payload(
    columns: dict[str, jnp.ndarray], match_row: jnp.ndarray
) -> dict[str, jnp.ndarray]:
    """Materialize build-side payload columns for matched probe rows."""
    idx = jnp.clip(match_row, 0, None)
    return {name: c[idx] for name, c in columns.items()}


def expand_join(
    build_sorted_keys64: jnp.ndarray,
    build_order: jnp.ndarray,
    build_nrows: jnp.ndarray,
    probe_key_cols: list[jnp.ndarray],
    probe_mask: jnp.ndarray,
    out_capacity: int,
):
    """M:N join expansion against a key-sorted build side.

    build_sorted_keys64: 64-bit mixed keys of build rows, ascending, with
    dead rows sorted to the end (callers pass +inf-like sentinel);
    build_order: original build row index per sorted position;
    Returns (out_probe_row [C] int32, out_build_row [C] int32, out_valid [C]
    bool, total matches [scalar int64], pair_starts [N] int64, pair_offs [N]
    int64). pair_starts/offs delimit each probe row's pair run in output-slot
    space (for scatter-free per-probe reductions, see probe_run_any). If
    total > out_capacity the output is truncated — the engine checks and
    re-runs with a larger capacity.
    """
    keys64 = join_keys64(probe_key_cols)
    # method='sort': the binary-search variant ('scan') lowers to a gather
    # loop that costs ~20x on TPU
    lo = jnp.searchsorted(
        build_sorted_keys64, keys64, side="left", method="sort"
    )
    hi = jnp.searchsorted(
        build_sorted_keys64, keys64, side="right", method="sort"
    )
    # dead build rows occupy sorted positions [build_nrows, nb) (they carry
    # int64-max placeholders); clamping keeps a live int64-max probe key
    # from matching them
    n_live = build_nrows.astype(lo.dtype)
    lo = jnp.minimum(lo, n_live)
    hi = jnp.minimum(hi, n_live)
    cnt = jnp.where(probe_mask, (hi - lo).astype(jnp.int64), 0)
    offs = jnp.cumsum(cnt)  # inclusive prefix sum
    total = offs[-1] if cnt.shape[0] > 0 else jnp.zeros((), jnp.int64)
    starts = offs - cnt  # exclusive
    # for each output slot t: probe row p = first row with offs[p] > t
    t = jnp.arange(out_capacity, dtype=jnp.int64)
    p = jnp.searchsorted(offs, t, side="right", method="sort").astype(jnp.int32)
    pc = jnp.clip(p, 0, cnt.shape[0] - 1)
    k = t - starts[pc]
    b_sorted_pos = (lo[pc].astype(jnp.int64) + k).astype(jnp.int32)
    out_valid = t < total
    nb = build_order.shape[0]
    out_build_row = build_order[jnp.clip(b_sorted_pos, 0, nb - 1)]
    return pc, out_build_row, out_valid, total, starts, offs


def probe_run_any(pair_ok: jnp.ndarray, starts: jnp.ndarray, offs: jnp.ndarray):
    """Per-probe-row OR over its pair run [starts, offs) in output-slot
    space — the scatter-free replacement for `.at[probe].max(pair_ok)`
    (cumsum + two monotone gathers instead of a ~1s TPU scatter)."""
    c = jnp.cumsum(pair_ok.astype(jnp.int64))
    cap = c.shape[0]

    def upto(x):
        return jnp.where(x > 0, c[jnp.clip(x - 1, 0, cap - 1)], 0)

    return (upto(jnp.minimum(offs, cap)) - upto(jnp.minimum(starts, cap))) > 0


def sort_build_side(key_cols: list[jnp.ndarray], mask: jnp.ndarray):
    """Sort build rows by mixed 64-bit key for expand_join; dead rows
    strictly last (deadness is a separate leading sort operand, so live
    rows whose key happens to equal int64 max still precede every dead
    row; expand_join then clamps searchsorted ranges to the live count)."""
    keys64 = join_keys64(key_cols)
    n = keys64.shape[0]
    dead = (~mask).astype(jnp.int32)
    sdead, skeys, order = jax.lax.sort(
        (dead, keys64, jnp.arange(n, dtype=jnp.int32)), num_keys=2
    )
    # dead tail carries int64 max so the array stays nondecreasing for
    # the binary search (live rows can also hold int64 max — harmless,
    # the clamp excludes the tail)
    skeys = jnp.where(sdead == 0, skeys, jnp.iinfo(jnp.int64).max)
    return skeys, order
