"""Window-function kernels: segmented scans over sorted partitions.

Reference surface: the vectorized window operator
(src/sql/engine/window_function, ObWindowFunctionVecOp) which materializes
partitions and evaluates ranking/aggregate functions per frame. The TPU
redesign sorts the whole batch once by (partition keys, order keys) —
masked-out rows to the tail — and then every window function is a
branch-free segmented scan over the sorted array:

  row_number  position - segment start + 1
  rank        peer-group start - segment start + 1
  dense_rank  segmented count of peer-group starts
  sum/count   running: segmented cumsum read at the END of the peer group
              (the SQL default frame RANGE UNBOUNDED PRECEDING..CURRENT ROW
              includes peers); whole-partition when there is no ORDER BY
  min/max     segmented associative scan (flag, value) pairs

Results scatter back to the original row positions, so the operator is
order-preserving like the reference's. Static shapes throughout; dead rows
ride along masked and cannot influence any frame because all value
accumulations are masked to the aggregate's identity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def boundaries(sorted_keys: list[jnp.ndarray]) -> jnp.ndarray:
    """True where any key column differs from the previous row (or row 0)."""
    n = sorted_keys[0].shape[0] if sorted_keys else 0
    if not sorted_keys:
        return jnp.zeros(0, jnp.bool_)
    new = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
    for k in sorted_keys:
        d = jnp.concatenate([jnp.ones(1, jnp.bool_), k[1:] != k[:-1]])
        new = new | d
    return new


def segment_starts(new_seg: jnp.ndarray) -> jnp.ndarray:
    """Index of the segment's first row, per row (int64)."""
    idx = jnp.arange(new_seg.shape[0], dtype=jnp.int64)
    return lax.cummax(jnp.where(new_seg, idx, 0))


def peer_ends(new_peer: jnp.ndarray) -> jnp.ndarray:
    """Index of the peer group's last row, per row (int64)."""
    n = new_peer.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    arr = jnp.where(new_peer, idx, n)
    # min over j >= i of boundary positions, then shift to "strictly after"
    suffix_min = lax.cummin(arr[::-1])[::-1]
    after = jnp.concatenate([suffix_min[1:], jnp.full(1, n, dtype=jnp.int64)])
    return after - 1


def segmented_cumsum(values: jnp.ndarray, seg_start: jnp.ndarray) -> jnp.ndarray:
    """Inclusive running sum within each segment. `values` must already be
    masked (dead/NULL rows contribute the identity 0)."""
    c = jnp.cumsum(values)
    return c - c[seg_start] + values[seg_start]


def segmented_scan_minmax(
    values: jnp.ndarray, new_seg: jnp.ndarray, is_min: bool
) -> jnp.ndarray:
    """Inclusive segmented running min/max; masked rows must carry the
    identity (+inf/-inf or int extremes) in `values`."""

    def comb(a, b):
        fa, va = a
        fb, vb = b
        v = jnp.where(fb, vb, jnp.minimum(va, vb) if is_min else jnp.maximum(va, vb))
        return fa | fb, v

    _, out = lax.associative_scan(comb, (new_seg, values))
    return out


def suffix_scan_minmax(
    values: jnp.ndarray, new_seg: jnp.ndarray, is_min: bool
) -> jnp.ndarray:
    """Inclusive segmented running min/max from the SEGMENT END backwards:
    out[i] = min/max over [i, seg_end]. Implemented by reversing, running
    the forward scan with reversed segment-start flags (= forward segment
    ENDS), and reversing back."""
    n = new_seg.shape[0]
    # forward seg-last flag: next row starts a new segment (or is row n-1)
    seg_last = jnp.concatenate([new_seg[1:], jnp.ones(1, jnp.bool_)])
    out_rev = segmented_scan_minmax(values[::-1], seg_last[::-1], is_min)
    return out_rev[::-1]


def agg_identity(dtype, is_min: bool):
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return info.max if is_min else info.min
    return jnp.inf if is_min else -jnp.inf


