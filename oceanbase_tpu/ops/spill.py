"""Spill operators: sort/join/group-by over data larger than one device
batch, with host tmp-file runs between device passes.

Reference surface: the spill paths of the vectorized operators — external
merge sort via tmp files (sql/engine/sort), partitioned hash join
(ObHJPartition, sql/engine/join/hash_join) and hash-agg partitioning
(ob_hp_infras_vec_op.h), all backed by storage/tmp_file.

TPU redesign: the device processes fixed-capacity chunks (sorted runs,
hash partitions) and the host streams spilled segments — device compute
stays static-shaped, host memory stays bounded by the chunk size:

  external_sort       device-sorts chunks into runs, then streaming 2-way
                      merges of page-sized blocks (classic external merge)
  partitioned_groupby hash-partition rows to segment files, device
                      group-by per partition, concatenate partitions
  partitioned_join    hash-partition both sides, device join per
                      partition pair (ObHJPartition analog)

Keys are int64 (dict codes / dates / ints — the engine's universal key
domain).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..storage.tmp_file import TmpFileManager
from .hashing import next_pow2


def pack_sort_key(cols: list[np.ndarray], descending: list[bool]) -> np.ndarray:
    """Pack multiple int columns into one orderable uint64 composite.

    Each column is offset to non-negative and bit-packed MSB-first; a
    descending column packs its complement. Raises if the combined bit
    width exceeds 64 (callers fall back to single-key sorts)."""
    widths = []
    shifted = []
    for c, desc in zip(cols, descending):
        c = c.astype(np.int64)
        lo, hi = int(c.min()), int(c.max())
        span = hi - lo
        w = max(1, int(span).bit_length())
        v = (c - lo).astype(np.uint64)
        if desc:
            v = np.uint64(span) - v
        widths.append(w)
        shifted.append(v)
    if sum(widths) > 64:
        raise ValueError(f"sort key too wide: {sum(widths)} bits")
    out = np.zeros(len(cols[0]), dtype=np.uint64)
    for v, w in zip(shifted, widths):
        out = (out << np.uint64(w)) | v
    return out


@jax.jit
def _device_sort_chunk(key: jnp.ndarray):
    return jnp.argsort(key)


class _RunCursor:
    """Streams one sorted run (a list of page segment files) page by page;
    holds at most one page in memory."""

    def __init__(self, pages: list[str], tmp: TmpFileManager):
        self.pages = pages
        self.tmp = tmp
        self.cur: dict[str, np.ndarray] | None = None
        self.pos = 0
        self._advance()

    def _advance(self):
        while self.pages and (
            self.cur is None or self.pos >= len(self.cur["__key__"])
        ):
            path = self.pages.pop(0)
            self.cur = self.tmp.read_segment(path)
            self.tmp.free_segment(path)
            self.pos = 0
        if self.cur is not None and self.pos >= len(self.cur["__key__"]):
            self.cur = None

    @property
    def head(self):
        return None if self.cur is None else self.cur["__key__"][self.pos]

    def take_until(self, limit_key, max_rows: int) -> dict[str, np.ndarray]:
        """Consume up to max_rows rows with key <= limit_key (or all
        remaining in the current page if limit_key is None)."""
        k = self.cur["__key__"]
        end = min(self.pos + max_rows, len(k))
        if limit_key is not None:
            end = min(end, self.pos + int(np.searchsorted(
                k[self.pos:end], limit_key, side="right")))
            end = max(end, self.pos + 1)
        out = {c: v[self.pos:end] for c, v in self.cur.items()}
        self.pos = end
        self._advance()
        return out


def external_sort(
    cols: dict[str, np.ndarray],
    key: np.ndarray,
    chunk_rows: int,
    tmp: TmpFileManager,
    page_rows: int | None = None,
) -> dict[str, np.ndarray]:
    """Sort columns by an int/uint key using bounded working memory.

    Device-sorts `chunk_rows`-sized runs spilled as page files, then
    streaming 2-way merges that hold O(page_rows) rows per input run and
    flush output pages as they fill — classic external merge sort. (The
    returned dict materializes the final order; callers sorting beyond
    host memory consume the final run's pages instead.)"""
    n = len(key)
    page_rows = page_rows or max(1024, chunk_rows // 8)
    names = list(cols)

    # phase 1: sorted runs (device argsort per chunk), paged on disk
    runs: list[list[str]] = []
    for s in range(0, n, chunk_rows):
        e = min(s + chunk_rows, n)
        order = np.asarray(_device_sort_chunk(jnp.asarray(key[s:e])))
        pages = []
        for ps in range(0, e - s, page_rows):
            pe = min(ps + page_rows, e - s)
            pidx = order[ps:pe]
            seg = {"__key__": key[s:e][pidx]}
            for c in names:
                seg[c] = cols[c][s:e][pidx]
            pages.append(tmp.write_segment(seg))
        runs.append(pages)
    if not runs:
        return {c: cols[c][:0] for c in names} | {"__key__": key[:0]}

    def merge(pa: list[str], pb: list[str]) -> list[str]:
        a, b = _RunCursor(pa, tmp), _RunCursor(pb, tmp)
        out_pages: list[str] = []
        buf: list[dict[str, np.ndarray]] = []
        buffered = 0

        def flush():
            nonlocal buf, buffered
            if buf:
                merged = {
                    k: np.concatenate([p[k] for p in buf]) for k in buf[0]
                }
                out_pages.append(tmp.write_segment(merged))
                buf, buffered = [], 0

        while a.head is not None or b.head is not None:
            if b.head is None or (a.head is not None and a.head <= b.head):
                part = a.take_until(b.head, page_rows)
            else:
                part = b.take_until(a.head, page_rows)
            buf.append(part)
            buffered += len(part["__key__"])
            if buffered >= page_rows:
                flush()
        flush()
        return out_pages

    while len(runs) > 1:
        nxt = []
        for i in range(0, len(runs) - 1, 2):
            nxt.append(merge(runs[i], runs[i + 1]))
        if len(runs) % 2:
            nxt.append(runs[-1])
        runs = nxt

    parts = []
    for path in runs[0]:
        parts.append(tmp.read_segment(path))
        tmp.free_segment(path)
    return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}


def _partition(
    cols: dict[str, np.ndarray], key: np.ndarray, n_parts: int,
    tmp: TmpFileManager,
) -> list[list[str]]:
    """Hash-partition rows into per-partition segment files."""
    h = (key.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)) >> np.uint64(33)
    part = (h % np.uint64(n_parts)).astype(np.int64)
    segs: list[list[str]] = [[] for _ in range(n_parts)]
    for p in range(n_parts):
        m = part == p
        if m.any():
            seg = {c: cols[c][m] for c in cols} | {"__key__": key[m]}
            segs[p].append(tmp.write_segment(seg))
    return segs


@partial(jax.jit, static_argnums=(2,))
def _device_groupby_sum(key: jnp.ndarray, vals: jnp.ndarray, ts: int):
    from .hashagg import assign_group_slots

    sel = jnp.ones(key.shape[0], dtype=jnp.bool_)
    row_slot, slot_used, slot_row = assign_group_slots([key], sel, ts)
    sums = jnp.zeros(ts, dtype=jnp.int64).at[
        jnp.where(sel, row_slot, ts)
    ].add(vals.astype(jnp.int64), mode="drop")
    cnts = jnp.zeros(ts, dtype=jnp.int64).at[
        jnp.where(sel, row_slot, ts)
    ].add(1, mode="drop")
    rep = jnp.clip(slot_row, 0, key.shape[0] - 1)
    keys_out = jnp.where(slot_used, key[rep], 0)
    return keys_out, sums, cnts, slot_used


def partitioned_groupby_sum(
    key: np.ndarray, vals: np.ndarray, n_parts: int, tmp: TmpFileManager
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """SUM/COUNT group-by over arbitrary row counts: hash partitions spill
    to tmp files, each partition aggregates on device. Returns (keys,
    sums, counts)."""
    segs = _partition({"v": vals}, key, n_parts, tmp)
    ks, ss, cs = [], [], []
    for plist in segs:
        if not plist:
            continue
        seg = tmp.read_segment(plist[0])
        tmp.free_segment(plist[0])
        k, v = seg["__key__"], seg["v"]
        ts = next_pow2(max(2 * len(np.unique(k)), 16))
        ko, so, co, used = (np.asarray(x) for x in _device_groupby_sum(
            jnp.asarray(k), jnp.asarray(v), ts))
        ks.append(ko[used])
        ss.append(so[used])
        cs.append(co[used])
    if not ks:
        z = np.zeros(0, np.int64)
        return z, z, z
    return np.concatenate(ks), np.concatenate(ss), np.concatenate(cs)


@partial(jax.jit, static_argnums=(4,))
def _device_join_sum(lk: jnp.ndarray, lv: jnp.ndarray, rk: jnp.ndarray,
                     rv: jnp.ndarray, ts: int):
    from .join import build_hash_table, hash_join_probe

    rsel = jnp.ones(rk.shape[0], dtype=jnp.bool_)
    lsel = jnp.ones(lk.shape[0], dtype=jnp.bool_)
    slot_key, slot_row = build_hash_table([rk], rsel, ts)
    match = hash_join_probe(slot_key, slot_row, [rk], [lk], lsel)
    hit = match >= 0
    idx = jnp.clip(match, 0, None)
    prod = jnp.where(hit, lv.astype(jnp.int64) * rv[idx].astype(jnp.int64), 0)
    return jnp.sum(prod), jnp.sum(hit, dtype=jnp.int64)


def partitioned_join_sum(
    lkey: np.ndarray, lval: np.ndarray,
    rkey: np.ndarray, rval: np.ndarray,
    n_parts: int, tmp: TmpFileManager,
) -> tuple[int, int]:
    """Unique-build hash join over arbitrary sizes: co-partition both
    sides to tmp files, join each partition pair on device. Returns
    (sum(lval*rval over matches), match count) — the aggregate form keeps
    the demo self-checking; generalization follows the same partition
    loop."""
    lsegs = _partition({"v": lval}, lkey, n_parts, tmp)
    rsegs = _partition({"v": rval}, rkey, n_parts, tmp)
    total = np.int64(0)
    matches = np.int64(0)
    for p in range(n_parts):
        if not lsegs[p] or not rsegs[p]:
            for plist in (lsegs[p], rsegs[p]):
                for path in plist:
                    tmp.free_segment(path)
            continue
        ls = tmp.read_segment(lsegs[p][0])
        rs = tmp.read_segment(rsegs[p][0])
        tmp.free_segment(lsegs[p][0])
        tmp.free_segment(rsegs[p][0])
        ts = next_pow2(max(2 * len(rs["__key__"]), 16))
        s, m = _device_join_sum(
            jnp.asarray(ls["__key__"]), jnp.asarray(ls["v"]),
            jnp.asarray(rs["__key__"]), jnp.asarray(rs["v"]), ts)
        total += np.int64(s)
        matches += np.int64(m)
    return int(total), int(matches)
