"""Vectorized group-by aggregation kernels.

Reference surface: ObHashGroupByVecOp (sql/engine/aggregate) + the new
aggregate framework (src/share/aggregate/agg_ctx.h) and its adaptive bypass
for low-NDV keys (ob_adaptive_bypass_ctrl.h). The TPU redesign replaces
pointer-chasing hash tables with two scatter-native strategies:

1. direct:  bounded key domains bit-pack into a dense int (ops/hashing.py);
   the packed key IS the slot — aggregation is one scatter-add per agg.
   This is the TPU analog of the reference's bypass/"no hash table" path.

2. hashed:  arbitrary int64 keys go through vectorized open-addressing slot
   assignment: all rows probe in lockstep; each round, unclaimed rows try to
   claim their probe slot with a scatter-min arbitration, losers against a
   different key advance their probe, losers against the same key match next
   round. Terminates in <= table_size rounds (lax.while_loop, static shapes).

Both return fixed-capacity group tables (capacity + occupancy mask), the
static-shape discipline XLA needs; the engine layer sizes capacity from
optimizer NDV estimates and retries bigger on overflow (the spill analog —
reference spills to tmp files, we respill to a larger compile).

All aggregates accumulate via segment scatter-adds/min/max which XLA lowers
to efficient TPU scatters. SUM of decimals stays in int64.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from .hashing import hash_combine, next_pow2

_I32_MAX = jnp.iinfo(jnp.int32).max
_I64_MAX = jnp.iinfo(jnp.int64).max
_I64_MIN = jnp.iinfo(jnp.int64).min


def assign_group_slots(
    key_cols: list[jnp.ndarray], mask: jnp.ndarray, table_size: int
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Assign each live row a slot in an open-addressing table.

    Returns (row_slot [N] int32, slot_used [T] bool, slot_of_first_row [T]
    int32 — for materializing key columns per group via gather).
    Dead rows get slot -1.

    The table stores a 32-bit hash TAG per slot (TPUs emulate 64-bit int
    multiplies, so both the mix and the per-probe tag compare run 32-bit);
    "same key" additionally compares every real key column against the
    slot's first claimant, so tag collisions only cost an extra probe.
    """
    from .hashing import hash32_combine, inherit_vma

    n = key_cols[0].shape[0]
    ts = table_size
    tags = hash32_combine(key_cols).astype(jnp.int32)
    h = (tags.astype(jnp.uint32) & jnp.uint32(ts - 1)).astype(jnp.int32)

    rows = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        _, _, _, pending, probe, _ = state
        return jnp.logical_and(jnp.any(pending), probe < ts)

    def body(state):
        slot_tag, slot_row, row_slot, pending, probe, probe_of = state
        pos = ((h + probe_of) & (ts - 1)).astype(jnp.int32)
        at_used = slot_row[pos] >= 0
        at_tag = slot_tag[pos]
        # exact key equality vs the slot's first claimant (the tag alone
        # could merge distinct keys; the reference compares real keys too)
        at_row = jnp.clip(slot_row[pos], 0, n - 1)
        exact = jnp.ones(n, dtype=jnp.bool_)
        for c in key_cols:
            exact = exact & (c[at_row] == c)
        same = pending & at_used & (at_tag == tags) & exact
        # claim arbitration: lowest row id wins each empty slot
        claim = jnp.full(ts, _I32_MAX, dtype=jnp.int32)
        claim = claim.at[jnp.where(pending & ~at_used, pos, ts)].min(
            rows, mode="drop"
        )
        winner = pending & ~at_used & (claim[pos] == rows)
        # winners write their tag + row id
        wpos = jnp.where(winner, pos, ts)
        slot_tag = slot_tag.at[wpos].set(tags, mode="drop")
        slot_row = slot_row.at[wpos].set(rows, mode="drop")
        matched = winner | same
        row_slot = jnp.where(matched, pos, row_slot)
        pending = pending & ~matched
        # advance probe only for rows that saw a different-key occupied slot
        advance = pending & at_used & ~((at_tag == tags) & exact)
        probe_of = probe_of + advance.astype(jnp.int32)
        return slot_tag, slot_row, row_slot, pending, probe + 1, probe_of

    init = (
        inherit_vma(jnp.zeros(ts, dtype=jnp.int32), tags),  # slot_tag
        inherit_vma(jnp.full(ts, -1, dtype=jnp.int32), tags),  # slot_row
        inherit_vma(jnp.full(n, -1, dtype=jnp.int32), tags),  # row_slot
        mask,  # pending
        inherit_vma(jnp.zeros((), dtype=jnp.int32), tags),  # round counter
        inherit_vma(jnp.zeros(n, dtype=jnp.int32), tags),  # per-row probe
    )
    slot_tag, slot_row, row_slot, pending, _, _ = jax.lax.while_loop(
        cond, body, init
    )
    slot_used = slot_row >= 0
    return row_slot, slot_used, slot_row


@dataclass(frozen=True)
class AggSpec:
    """One aggregate: op in {sum, count, min, max}; values = input array
    (ignored for count). Decimal sums pass int64 values."""

    op: str
    name: str


def _apply_agg(op: str, row_slot, mask, values, table_size: int):
    idx = jnp.where(mask, row_slot, table_size)  # dead rows dropped
    if op == "count":
        out = jnp.zeros(table_size, dtype=jnp.int64)
        return out.at[idx].add(1, mode="drop")
    if op == "sum":
        acc_dtype = (
            jnp.int64
            if jnp.issubdtype(values.dtype, jnp.integer)
            else values.dtype
        )
        out = jnp.zeros(table_size, dtype=acc_dtype)
        return out.at[idx].add(values.astype(acc_dtype), mode="drop")
    if op == "min":
        init = (
            jnp.iinfo(values.dtype).max
            if jnp.issubdtype(values.dtype, jnp.integer)
            else jnp.inf
        )
        out = jnp.full(table_size, init, dtype=values.dtype)
        return out.at[idx].min(values, mode="drop")
    if op == "max":
        init = (
            jnp.iinfo(values.dtype).min
            if jnp.issubdtype(values.dtype, jnp.integer)
            else -jnp.inf
        )
        out = jnp.full(table_size, init, dtype=values.dtype)
        return out.at[idx].max(values, mode="drop")
    raise NotImplementedError(op)


def groupby_hash(
    key_cols: list[jnp.ndarray],
    mask: jnp.ndarray,
    agg_ops: list[str],
    agg_values: list[jnp.ndarray | None],
    table_size: int,
):
    """General hash group-by.

    Returns (group_keys: list of arrays [T] — key columns gathered from each
    group's first row, slot_used [T], aggs: list of arrays [T]).
    table_size must be a power of two >= 2 * expected NDV.
    """
    assert table_size == next_pow2(table_size)
    row_slot, slot_used, slot_row = assign_group_slots(key_cols, mask, table_size)
    gk = [
        jnp.where(slot_used, c[jnp.clip(slot_row, 0, c.shape[0] - 1)], 0)
        for c in key_cols
    ]
    aggs = [
        _apply_agg(op, row_slot, mask, v, table_size)
        for op, v in zip(agg_ops, agg_values)
    ]
    return gk, slot_used, aggs


def groupby_direct(
    packed_keys: jnp.ndarray,
    domain: int,
    mask: jnp.ndarray,
    agg_ops: list[str],
    agg_values: list[jnp.ndarray | None],
):
    """Direct-addressed group-by for bit-packed bounded keys.

    packed_keys in [0, domain). Returns (slot_used [domain], aggs [domain]).
    The group's key columns are recovered by unpacking the slot index.

    Computed as `domain` MASKED REDUCTIONS per aggregate, not scatters:
    on TPU a fused masked-sum sweep over 8M rows costs ~2.4ms for 8 slots
    while one scatter-add costs ~1.1s. The reductions share the row scan
    (XLA fuses them), so cost scales with domain * passes, which is why the
    engine caps the direct path at a small domain.
    """
    aggs: list[jnp.ndarray] = []
    slot_is = [packed_keys == g for g in range(domain)]
    counts = jnp.stack(
        [jnp.sum(mask & is_g, dtype=jnp.int64) for is_g in slot_is]
    )
    slot_used = counts > 0
    for op, v in zip(agg_ops, agg_values):
        if op == "count":
            aggs.append(counts)
            continue
        if op == "sum":
            acc = (
                jnp.int64
                if jnp.issubdtype(v.dtype, jnp.integer)
                else v.dtype
            )
            aggs.append(jnp.stack([
                jnp.sum(jnp.where(mask & is_g, v, 0).astype(acc))
                for is_g in slot_is
            ]))
        elif op == "min":
            ident = (
                jnp.iinfo(v.dtype).max
                if jnp.issubdtype(v.dtype, jnp.integer) else jnp.inf
            )
            aggs.append(jnp.stack([
                jnp.min(jnp.where(mask & is_g, v, ident)) for is_g in slot_is
            ]))
        elif op == "max":
            ident = (
                jnp.iinfo(v.dtype).min
                if jnp.issubdtype(v.dtype, jnp.integer) else -jnp.inf
            )
            aggs.append(jnp.stack([
                jnp.max(jnp.where(mask & is_g, v, ident)) for is_g in slot_is
            ]))
        else:
            raise NotImplementedError(op)
    return slot_used, aggs


def distinct_first_mask(
    key_vals: list[jnp.ndarray], val: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """First-occurrence mask for DISTINCT aggregates: True for exactly one
    live row per (group keys, value) combination, in ORIGINAL row order.

    The reference routes distinct aggregates through a dedicated hash-set
    pass (sql/engine/aggregate distinct-agg infra); the TPU redesign is the
    usual scatter-free recipe: one combined sort with the row index as the
    trailing operand, run-boundary detection, and an argsort-based inverse
    permutation to map the per-run winner bit back."""
    from .sort import split_sort_key

    n = mask.shape[0]
    dead = (~mask).astype(jnp.int32)
    planes: list[jnp.ndarray] = [dead]
    for k in (*key_vals, val):
        planes.extend(split_sort_key(k))
    ops = tuple(planes) + (jnp.arange(n, dtype=jnp.int32),)
    sorted_ = jax.lax.sort(ops, num_keys=len(ops) - 1)
    sdead = sorted_[0]
    sidx = sorted_[-1]
    new_run = jnp.zeros(n, jnp.bool_)
    for sv in sorted_[:-1]:
        new_run = new_run | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), sv[1:] != sv[:-1]]
        )
    first = new_run & (sdead == 0)
    return first[jnp.argsort(sidx)]


def sort_groupby(
    key_cols: list[jnp.ndarray],
    mask: jnp.ndarray,
    agg_ops: list[str],
    agg_values: list[jnp.ndarray | None],
    agg_masks: list[jnp.ndarray | None] = None,
):
    """Sort-based group-by: the TPU default for unbounded key domains.

    One multi-operand lexicographic sort (dead rows last), segment
    boundaries by exact key comparison, then every aggregate is a
    segmented cumsum / associative scan read at the segment end — no hash
    table, no scatter, no capacity/overflow: the output reuses the input
    capacity with one live row per group (at its segment start, in sorted
    key order).

    Returns (group_keys: list [N] arrays, sel [N] bool group-start mask,
    aggs: list [N] arrays, order [N] int32 the sort permutation).
    agg_masks[i] (optional) restricts which rows feed aggregate i (SQL
    null-skipping); rows outside `mask` never contribute.
    """
    from .sort import rebuild_i64, split_sort_key
    from .window import peer_ends, segmented_cumsum, segmented_scan_minmax

    n = key_cols[0].shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # int64 keys split into i32 planes: multi-i64-operand sorts hit a
    # superlinear cliff past ~16M rows on v5e (see ops/sort.py)
    planes: list[jnp.ndarray] = []
    plane_spec: list[tuple[object, int]] = []  # (orig dtype, nplanes)
    for k in key_cols:
        p = split_sort_key(k)
        plane_spec.append((k.dtype, len(p)))
        planes.extend(p)
    operands = (~mask,) + tuple(planes) + (idx,)
    sorted_ = jax.lax.sort(operands, num_keys=1 + len(planes))
    sdead = sorted_[0]
    sp = list(sorted_[1:-1])
    order = sorted_[-1]
    ssel = ~sdead
    # reconstruct the sorted key columns from their planes
    skeys: list[jnp.ndarray] = []
    i = 0
    for dtype, np_ in plane_spec:
        if np_ == 2:
            skeys.append(rebuild_i64(sp[i], sp[i + 1]))
        else:
            skeys.append(sp[i].astype(dtype))
        i += np_

    new_seg = jnp.zeros(n, jnp.bool_).at[0].set(True)
    for k in skeys:
        new_seg = new_seg | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), k[1:] != k[:-1]]
        )
    # dead rows sort last; the first dead row must not join the previous
    # live segment
    new_seg = new_seg | jnp.concatenate(
        [jnp.ones(1, jnp.bool_), sdead[1:] != sdead[:-1]]
    )
    pos = jnp.arange(n, dtype=jnp.int64)
    seg_start = jax.lax.cummax(jnp.where(new_seg, pos, 0))
    seg_end = peer_ends(new_seg)

    # ONE packed row-gather brings every agg value/mask into sorted order
    # (per-agg element gathers at int64 cost ~42M/s each; the packed form
    # moves all of them at ~175M rows/s — ops/gather.py)
    from .gather import gather_rows

    to_sort: dict = {}
    for i, (v, op) in enumerate(zip(agg_values, agg_ops)):
        if v is not None:
            to_sort[("v", i)] = v
        am = agg_masks[i] if agg_masks is not None else None
        if am is not None:
            to_sort[("m", i)] = am
    sorted_in = gather_rows(to_sort, order) if to_sort else {}

    # accumulate every per-agg running array, then ONE packed gather at
    # the segment ends materializes all the results together
    running: dict = {}
    for i, (op, v) in enumerate(zip(agg_ops, agg_values)):
        am_s = sorted_in.get(("m", i))
        vm = ssel if am_s is None else (ssel & am_s)
        if op == "count":
            running[i] = segmented_cumsum(vm.astype(jnp.int64), seg_start)
            continue
        sv = sorted_in[("v", i)]
        if op == "sum":
            acc = (
                jnp.int64
                if jnp.issubdtype(sv.dtype, jnp.integer)
                else sv.dtype
            )
            mv = jnp.where(vm, sv.astype(acc), 0)
            running[i] = segmented_cumsum(mv, seg_start)
        elif op in ("min", "max"):
            is_min = op == "min"
            ident = (
                (jnp.iinfo(sv.dtype).max if is_min else jnp.iinfo(sv.dtype).min)
                if jnp.issubdtype(sv.dtype, jnp.integer)
                else (jnp.inf if is_min else -jnp.inf)
            )
            mv = jnp.where(vm, sv, ident)
            running[i] = segmented_scan_minmax(mv, new_seg, is_min)
        else:
            raise NotImplementedError(op)
    ends = gather_rows(running, seg_end) if running else {}
    aggs_out = [ends[i] for i in range(len(agg_ops))]
    sel = new_seg & ssel
    return skeys, sel, aggs_out, order


def scalar_aggregate(
    mask: jnp.ndarray, agg_ops: list[str], agg_values: list[jnp.ndarray | None]
):
    """Ungrouped aggregation (reference: ObScalarAggregateOp) — one masked
    reduction per agg; XLA fuses these with the producing expressions."""
    out = []
    for op, v in zip(agg_ops, agg_values):
        if op == "count":
            out.append(jnp.sum(mask, dtype=jnp.int64))
            continue
        if op == "approx_ndv":
            from .hll import hll_count

            out.append(hll_count(v, mask))
            continue
        if op == "sum":
            acc = (
                jnp.int64 if jnp.issubdtype(v.dtype, jnp.integer) else v.dtype
            )
            out.append(jnp.sum(jnp.where(mask, v, 0).astype(acc)))
        elif op == "min":
            init = (
                jnp.iinfo(v.dtype).max
                if jnp.issubdtype(v.dtype, jnp.integer)
                else jnp.inf
            )
            out.append(jnp.min(jnp.where(mask, v, init)))
        elif op == "max":
            init = (
                jnp.iinfo(v.dtype).min
                if jnp.issubdtype(v.dtype, jnp.integer)
                else -jnp.inf
            )
            out.append(jnp.max(jnp.where(mask, v, init)))
        else:
            raise NotImplementedError(op)
    return out
