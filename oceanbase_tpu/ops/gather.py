"""Packed multi-column row gather — the join/sort payload hot path.

Reference surface: the row-payload materialization of the vectorized hash
join (ObHashJoinVecOp probe output, sql/engine/join/hash_join) and the
generic permutation writebacks of sort/window operators.

Why this exists (measured on v5e via the axon tunnel, 33M probes):
XLA lowers a 1-D element gather to ~100M elements/s regardless of table
size or index order (int64: 42M/s) — each column of a join payload paid
that full price. A 2-D ROW gather from an (N, K) int32 matrix runs at
~175M rows/s for K=8 (1.4B values/s): the minor dimension is dense, so
the gather vectorizes across lanes. So: bitcast every payload column into
int32 "planes" (int64/float64 -> 2 planes, int32/bool/int8 -> 1), pack
the planes into (N, <=8) matrices, row-gather, unpack. The packing itself
is elementwise VPU work that XLA fuses; K=16 regresses (44M rows/s), so
plane groups cap at 8.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_GROUP = 8  # planes per row-gather (K=8 is the measured sweet spot)


def _to_planes(a: jnp.ndarray) -> list[jnp.ndarray] | None:
    """Split one column into int32 planes (bit-preserving). None = this
    dtype must not be packed (f64 bitcast-convert is rejected by the TPU
    AOT x64-rewriting pass; floats keep the element gather)."""
    if a.dtype == jnp.int64 or a.dtype == jnp.uint64:
        lo = a.astype(jnp.int32)  # wrap-around truncation: low 32 bits
        hi = (a >> 32).astype(jnp.int32)
        return [lo, hi]
    if a.dtype == jnp.float64 or a.dtype == jnp.float32:
        return None
    if a.dtype == jnp.bool_:
        return [a.astype(jnp.int32)]
    return [a.astype(jnp.int32)]


def _from_planes(planes: list[jnp.ndarray], dtype) -> jnp.ndarray:
    if dtype == jnp.int64 or dtype == jnp.uint64:
        lo, hi = planes
        v = (hi.astype(jnp.int64) << 32) | (
            lo.astype(jnp.int64) & jnp.int64(0xFFFFFFFF)
        )
        return v.astype(dtype)
    if dtype == jnp.bool_:
        return planes[0] != 0
    return planes[0].astype(dtype)


def gather_rows(
    cols: dict[str, jnp.ndarray], idx: jnp.ndarray
) -> dict[str, jnp.ndarray]:
    """{name: column[idx]} for every column, via packed row gathers.

    Columns must share a common length. A single int32-plane column skips
    packing (a (N,1) row gather is no better than the element gather)."""
    if not cols:
        return {}
    out: dict[str, jnp.ndarray] = {}
    plan: list[tuple[str, object, int]] = []  # (name, dtype, nplanes)
    planes: list[jnp.ndarray] = []
    for name, a in cols.items():
        p = _to_planes(a)
        if p is None:
            out[name] = a[idx]  # unpackable dtype: element gather
            continue
        plan.append((name, a.dtype, len(p)))
        planes.extend(p)
    if len(planes) == 1:
        name, dtype, _ = plan[0]
        out[name] = cols[name][idx]
        return out
    out_planes: list[jnp.ndarray] = []
    for g in range(0, len(planes), _GROUP):
        group = planes[g:g + _GROUP]
        packed = jnp.stack(group, axis=1)  # (N, K) int32
        got = packed[idx]  # (M, K) row gather — the fast path
        out_planes.extend(got[:, j] for j in range(len(group)))
    i = 0
    for name, dtype, np_ in plan:
        out[name] = _from_planes(out_planes[i:i + np_], dtype)
        i += np_
    return out
