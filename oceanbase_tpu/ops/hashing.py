"""Key hashing / packing primitives for device hash tables.

Reference surface: OceanBase's murmur-based datum hashing feeding hash join /
group-by / exchange slice calc (sql/engine/px/ob_slice_calc.h:55, the hash
infrastructure in sql/engine/basic/ob_hp_infras_vec_op.h). The TPU redesign
splits the problem:

- When key domains are statically small (dictionary-encoded columns, bounded
  ints), multiple keys BIT-PACK into one int32/int64 "direct key" whose value
  is its own perfect-hash slot — group-by becomes a scatter-add, no table.
- Otherwise keys hash-combine via a 64-bit finalizer (splitmix64) and feed
  open-addressing tables (see hashagg.py / join.py).

Everything is branch-free elementwise math the VPU eats whole.
"""

from __future__ import annotations

import jax.numpy as jnp

# splitmix64 finalizer constants
_C1 = jnp.uint64(0xBF58476D1CE4E5B9)
_C2 = jnp.uint64(0x94D049BB133111EB)
_GOLDEN = jnp.uint64(0x9E3779B97F4A7C15)

# murmur3 fmix32 constants
_M1 = jnp.uint32(0x85EBCA6B)
_M2 = jnp.uint32(0xC2B2AE35)
_GOLDEN32 = jnp.uint32(0x9E3779B9)


def mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer: avalanches a 64-bit value. uint64 in/out."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * _C1
    x = (x ^ (x >> 27)) * _C2
    return x ^ (x >> 31)


def hash_combine(columns: list[jnp.ndarray]) -> jnp.ndarray:
    """Combine N key columns into one avalanche-mixed uint64 hash."""
    h = jnp.zeros_like(columns[0], shape=columns[0].shape, dtype=jnp.uint64)
    for c in columns:
        h = mix64(h ^ (c.astype(jnp.uint64) + _GOLDEN))
    return h


def mix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 fmix32 finalizer. uint32 in/out.

    TPUs have no native 64-bit integer ALU (XLA emulates int64 multiplies
    with 32-bit pairs), so the hot hash paths — table build/probe, exchange
    slice-calc, bloom filters — run on 32-bit mixes. Key EQUALITY always
    re-checks the real key columns, so tag collisions cost a probe step,
    never correctness (same contract as the reference's murmur-based hash
    tables, ob_hp_infras_vec_op.h)."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * _M1
    x = (x ^ (x >> 13)) * _M2
    return x ^ (x >> 16)


def fold32(c: jnp.ndarray) -> jnp.ndarray:
    """Fold a key column to 32 bits, width-stable: an int32 column and an
    int64 column holding the same values fold identically (join sides may
    store the same key at different widths, and co-partitioning/bloom
    filters need both sides to agree). For narrow ints this is u ^ (u>>31)
    — exactly the xor-fold of the sign-extended 64-bit value."""
    if c.dtype.itemsize <= 4:
        i = c.astype(jnp.int32)
        return (i ^ (i >> 31)).astype(jnp.uint32)
    u = c.astype(jnp.uint64)
    return (u ^ (u >> 32)).astype(jnp.uint32)


def hash32_combine(columns: list[jnp.ndarray]) -> jnp.ndarray:
    """Combine N key columns into one avalanche-mixed uint32 hash."""
    h = jnp.zeros_like(columns[0], shape=columns[0].shape, dtype=jnp.uint32)
    for c in columns:
        h = mix32(h ^ (fold32(c) + _GOLDEN32))
    return h


def pack_keys(columns: list[jnp.ndarray], domains: list[int]) -> tuple[jnp.ndarray, int]:
    """Bit-pack bounded-domain key columns into a single dense int key.

    columns[i] must take values in [0, domains[i]). Returns (packed, space)
    where packed in [0, space) and space = prod(domains) rounded within the
    packing's bit layout. Packed keys are their own perfect hash — the
    direct-addressing fast path of group-by (the analog of the reference's
    adaptive bypass for low-NDV group-bys, ob_adaptive_bypass_ctrl.h).
    """
    bits = [max(1, int(d - 1).bit_length()) for d in domains]
    total = sum(bits)
    dtype = jnp.int32 if total <= 31 else jnp.int64
    packed = jnp.zeros_like(columns[0], dtype=dtype)
    shift = 0
    for c, b in zip(columns, bits):
        packed = packed | (c.astype(dtype) << shift)
        shift += b
    return packed, 1 << total


def next_pow2(n: int) -> int:
    return 1 << max(1, (int(n) - 1).bit_length())


def inherit_vma(arr: jnp.ndarray, ref: jnp.ndarray) -> jnp.ndarray:
    """Make a freshly-created array inherit `ref`'s varying-axis metadata.

    Under shard_map, `lax.while_loop` requires carry inits to carry the same
    varying-manual-axes annotation as the values the body produces; arrays
    minted with jnp.full/zeros inside an op are 'unvarying' and trip the
    checker. Adding a varying zero derived from a shard_map input fixes the
    annotation; numerically a no-op and XLA folds it outside shard_map.
    """
    z = ref.ravel()[0].astype(jnp.int32) * 0
    if arr.dtype == jnp.bool_:
        return arr ^ (z != 0)
    return arr + z.astype(arr.dtype)
