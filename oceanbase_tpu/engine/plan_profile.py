"""Profiled execution mode + the operator calibration record store.

The fused executable (executor.compile) is ONE XLA program — great for
serving, opaque for diagnosis: nothing in the system can say which
operator inside the plan burned the device time or blew its cardinality
estimate. This module runs a compiled plan as a segmented sequence of
per-operator jitted stages, split at the same `LogicalOp` node
boundaries `_number_nodes` assigns, with `block_until_ready` fences so
each stage yields wall-clocked device time, output cardinality and
output device bytes (joins/group-bys additionally get a measured
build/probe split). The segmented run produces the SAME root batch and
overflow vector as the fused program — bit-identical by test — so a
profiled execution serves its statement's result; nothing runs twice.

Profiling is never on the hot path: `PlanProfiler` samples per digest
(first RE-execution — a digest must recur before it pays a segmented
trace — then 1-in-N under ob_plan_profile_sample), is forced by
EXPLAIN ANALYZE and armed by the slow-query watermark, and every sample
folds into the bounded `OperatorProfileStore` keyed by
(digest, node_id, op_kind). Each record carries device-time/rows/bytes
histograms PLUS the optimizer's estimated cardinality captured at
compile time — an (estimate, actual) calibration pair, the data
contract the measurement-calibrated optimizer (ROADMAP item 5) reads.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from .executor import (
    ROOT_COMPACT,
    _children,
    _device_nbytes,
    _number_nodes,
    _unpack_qparams,
    compact_batch,
)

# log2 histogram buckets: bucket i holds values in [2^(i-1), 2^i)
_NB = 48


def _bucket(v) -> int:
    return min(int(max(v, 0)).bit_length(), _NB - 1)


def hist_quantile(hist, q: float) -> float:
    """Approximate quantile from a log2-bucket histogram (upper bound
    of the bucket the q-th observation falls in)."""
    total = sum(hist)
    if total <= 0:
        return 0.0
    want = q * total
    seen = 0
    for i, c in enumerate(hist):
        seen += c
        if seen >= want:
            return float(1 << i)
    return float(1 << (_NB - 1))


def _vec_project(op) -> bool:
    """True when a Project computes a vec_l2 distance column — the
    full-batch matmul that dominates the brute-force ANN route, and the
    measurement the optimizer's brute-side us/row rate comes from."""
    from ..expr import ir as E

    for _name, e in getattr(op, "exprs", ()) or ():
        if isinstance(e, E.Func) and e.name == "vec_l2":
            return True
    return False


def op_kind(op) -> str:
    """Display kind of one plan node (JoinOp carries its join kind —
    an anti join and an inner join calibrate very differently)."""
    k = type(op).__name__
    kind = getattr(op, "kind", None)
    if k in ("JoinOp", "SetOp") and kind:
        return f"{k[:-2] if k == 'JoinOp' else k}:{kind}"
    return k


def miss_factor(est, actual) -> float:
    """Symmetric misestimation ratio, floor-clamped so empty operators
    (0 rows either side) read as 1.0, never inf."""
    e = max(float(est), 1.0)
    a = max(float(actual), 1.0)
    return max(e / a, a / e)


# ---- segmented execution ----------------------------------------------------


@dataclass
class OpSample:
    """One operator's measurements from one profiled execution."""

    node_id: int
    op_kind: str
    device_us: float
    rows: int
    out_bytes: int
    build_us: float = 0.0
    probe_us: float = 0.0
    # rows of work the operator actually touched (candidate rows for an
    # IVF probe, full batch for a brute top-n) — the denominator the ANN
    # route costing calibrates us/row against; 0 = not tracked
    work_rows: int = 0


class SegmentedPlan:
    """Per-operator jitted stages for one PreparedPlan.

    Each stage re-emits exactly one plan node via Executor._emit_node
    with an emit stub that returns the already-computed child batches
    instead of recursing — the traced math per node is the same graph
    the fused program contains, so the segmented composition reproduces
    the fused result. Stages run in post-order (children first); the
    root output goes through the same compact_batch the fused run()
    applies, and the per-stage overflow counters stack over the same
    sorted overflow_nodes order — (out, ovf_vec) match the fused ABI.

    Segmentation follows the nodes the executor actually EMITS, not the
    logical tree: a clustered-FK aggregate absorbs its Join child and
    asks emit() for the join's own children directly, so the absorbed
    Join gets no stage and no sample (its work is inside the
    aggregate's measurement) — `absorbed` maps those node ids to the
    absorbing parent so EXPLAIN ANALYZE / coverage checks can say so.

    Stage tracing closes over the plan's PhysicalParams capacities, so
    the cache is invalidated whenever the plan recompiled (retries
    moved) — `stale()` checks exactly that.
    """

    def __init__(self, prepared):
        ex = prepared.executor
        plan = prepared.plan
        params = prepared.params
        self.nodes = _number_nodes(plan)
        id_of = {id(op): nid for nid, op in self.nodes.items()}
        self._spec = prepared._qparam_spec
        self.overflow_nodes = list(prepared.overflow_nodes)
        self._retries0 = getattr(prepared, "retries", 0)
        self._params = params
        self._warm = False

        # effective children: the nodes _emit_node will actually ask
        # emit() for. A clustered-FK aggregate bypasses its Join child
        # (executor._emit_clustered_agg emits ji.left / ji.right
        # itself), so the absorbed Join never executes as its own node.
        from ..sql.logical import Aggregate as _Agg, TopN as _TopN

        self.absorbed: dict[int, int] = {}

        def eff_children(op):
            nid = id_of[id(op)]
            if (isinstance(op, _Agg) and op.grouping_sets is None
                    and nid in params.clustered_aggs):
                ji = params.clustered_aggs[nid].ji
                self.absorbed[id_of[id(ji)]] = nid
                return (ji.left, ji.right)
            if isinstance(op, _TopN) and nid in params.vector_topns:
                # ANN top-n emits from the SCAN, fusing any intervening
                # Project/Filter into its own kernel — those nodes never
                # execute standalone, exactly like the absorbed join
                vs = params.vector_topns[nid]
                node = op.child
                while id(node) != id(vs.scan):
                    self.absorbed[id_of[id(node)]] = nid
                    node = node.child
                return (vs.scan,)
            return _children(op)

        # post-order over unique node ids: children before parents (a
        # shared subtree executes once; the fused trace CSEs it anyway)
        order: list[int] = []
        seen: set[int] = set()

        def walk(op):
            nid = id_of[id(op)]
            if nid in seen:
                return
            for c in eff_children(op):
                walk(c)
            if nid not in seen:
                seen.add(nid)
                order.append(nid)

        walk(plan)
        self.order = order
        self.root = id_of[id(plan)]
        self.stages = {}
        self.builders = {}
        for nid in order:
            op = self.nodes[nid]
            child_ids = tuple(id_of[id(c)] for c in eff_children(op))
            self.stages[nid] = (
                child_ids,
                jax.jit(self._make_stage(ex, op, child_ids, params, id_of)),
            )
            bf = self._make_build(op, clustered=nid in params.clustered_aggs)
            if bf is not None:
                self.builders[nid] = jax.jit(bf)

        def root_compact(out):
            return compact_batch(out, params.join_cap[ROOT_COMPACT])

        self._compact = jax.jit(root_compact)

    def stale(self, prepared) -> bool:
        """An overflow bump recompiled the plan: the stage closures
        baked the OLD capacities — rebuild before the next profile."""
        return (getattr(prepared, "retries", 0) != self._retries0
                or prepared.params is not self._params)

    def _make_stage(self, ex, op, child_ids, params, id_of):
        spec = self._spec

        def stage(inputs, child_outs, qparams):
            from ..expr import compile as expr_compile

            # the same parameter frame the fused run() installs: stage
            # expressions read bound literals through the global frame
            qp = _unpack_qparams(qparams, spec)
            prev = expr_compile.set_params(qp if qp else None)
            try:
                def emit(child, _inputs):
                    return child_outs[child_ids.index(id_of[id(child)])], {}

                out, ovf = ex._emit_node(op, inputs, emit, params, id_of)
            finally:
                expr_compile.set_params(prev)
            return out, ovf, jnp.sum(out.sel, dtype=jnp.int64)

        return stage

    def _make_build(self, op, clustered: bool = False):
        """Auxiliary build-phase-only program for joins/group-bys: the
        build side's key evaluation + sort, fenced separately so
        probe_us = device_us - build_us. A measured approximation (the
        merge-join fast path skips the sort in the real stage), honest
        enough to say WHICH side of a join dominates. Clustered-FK
        aggregates have no build phase (segment ranges are precomputed
        on the host) — no builder, probe_us == device_us."""
        from ..sql.logical import Aggregate as _Agg, JoinOp as _Join

        if clustered:
            return None
        spec = self._spec
        if isinstance(op, _Join) and op.right_keys:

            def jbuild(inputs, child_outs, qparams):
                from ..expr import compile as expr_compile
                from ..expr.compile import evaluate
                from ..ops.join import sort_build_side

                qp = _unpack_qparams(qparams, spec)
                prev = expr_compile.set_params(qp if qp else None)
                try:
                    right = child_outs[1]
                    rkeys = [evaluate(e, right)[0] for e in op.right_keys]
                    skeys, sorder = sort_build_side(rkeys, right.sel)
                finally:
                    expr_compile.set_params(prev)
                return skeys, sorder

            return jbuild
        if (isinstance(op, _Agg) and op.group_keys
                and op.grouping_sets is None):

            def gbuild(inputs, child_outs, qparams):
                from ..expr import compile as expr_compile
                from ..expr.compile import evaluate

                qp = _unpack_qparams(qparams, spec)
                prev = expr_compile.set_params(qp if qp else None)
                try:
                    child = child_outs[0]
                    _name, e = op.group_keys[0]
                    v, vv = evaluate(e, child)
                    if vv is not None:
                        v = jnp.where(vv, v, jnp.zeros_like(v))
                    out = jnp.sort(v)
                finally:
                    expr_compile.set_params(prev)
                return out

            return gbuild
        return None

    def run(self, inputs, qparams=()):
        """Execute every stage with fences; returns (out, ovf_vec,
        samples). samples is None when any capacity overflowed mid-run:
        the profile is abandoned but (out, ovf_vec) still carry the
        overflow counters, so the caller's normal redrive machinery
        takes over — a dropped sample, never a failed statement."""
        from .executor import _BATCH_COMPILE_LOCK
        from ..share.interrupt import checkpoint

        checkpoint()
        # first run traces every stage; set_params installs a process-
        # global frame during tracing, serialized exactly like the
        # batched-bucket traces
        lock = _BATCH_COMPILE_LOCK if not self._warm else None
        if lock is not None:
            lock.acquire()
        try:
            outs: dict[int, object] = {}
            ovf: dict[int, object] = {}
            samples: list[OpSample] = []
            for nid in self.order:
                child_ids, fn = self.stages[nid]
                childs = tuple(outs[c] for c in child_ids)
                t0 = time.perf_counter()
                out, novf, nrows = fn(inputs, childs, qparams)
                jax.block_until_ready(out)
                device_us = (time.perf_counter() - t0) * 1e6
                outs[nid] = out
                ovf.update(novf)
                build_us = 0.0
                bf = self.builders.get(nid)
                if bf is not None:
                    try:
                        tb = time.perf_counter()
                        jax.block_until_ready(
                            bf(inputs, childs, qparams))
                        build_us = (time.perf_counter() - tb) * 1e6
                    except Exception:
                        # untraceable build approximation (exotic key
                        # dtype): report probe-only, don't retry per run
                        self.builders.pop(nid, None)
                build_us = min(build_us, device_us)
                kind = op_kind(self.nodes[nid])
                work_rows = 0
                if kind == "TopN":
                    vs = self._params.vector_topns.get(nid)
                    if vs is not None:
                        # IVF route: centroid pass + padded candidate
                        # windows — the static work the kernel really does
                        kind = "VectorTopN"
                        work_rows = vs.lists + vs.nprobe * vs.max_list
                elif (kind == "Project" and childs
                        and _vec_project(self.nodes[nid])):
                    # brute route: the hoisted distance matmul ranks the
                    # whole padded batch (ordinary projections stay
                    # untracked — their us/row would skew the route rates)
                    kind = "VecDistance"
                    work_rows = int(childs[0].sel.shape[0])
                samples.append(OpSample(
                    node_id=nid,
                    op_kind=kind,
                    device_us=device_us,
                    rows=int(nrows),
                    out_bytes=int(_device_nbytes(out)),
                    build_us=build_us,
                    probe_us=max(device_us - build_us, 0.0),
                    work_rows=work_rows,
                ))
            t0 = time.perf_counter()
            out, oc = self._compact(outs[self.root])
            jax.block_until_ready(out.sel)
            # result compaction is part of the fused root's work:
            # charge it to the root operator's account
            samples[-1].device_us += (time.perf_counter() - t0) * 1e6
            ovf[ROOT_COMPACT] = oc
            ovf_vec = (
                jnp.stack([
                    ovf.get(n, jnp.zeros((), jnp.int64))
                    for n in self.overflow_nodes
                ])
                if self.overflow_nodes else jnp.zeros((0,), jnp.int64)
            )
            if any(int(v) > 0 for v in np.asarray(ovf_vec)):
                return out, ovf_vec, None
            self._warm = True
            return out, ovf_vec, samples
        finally:
            if lock is not None:
                lock.release()


def run_profiled(prepared, qparams=()):
    """Run one PreparedPlan through the segmented profiler. Returns
    (out, ovf_vec, samples) with the fused (out, ovf_vec) ABI; the
    SegmentedPlan caches on the prepared plan and rebuilds after any
    overflow recompile."""
    inputs = prepared._inputs()
    validate = getattr(prepared.jitted, "validate", None)
    if validate is not None:
        # warm artifact executable: the fused dispatch would raise
        # ArtifactStale from jit_call on these inputs. The segmented
        # stages trace fresh over ANY shapes, so without this mirror
        # check a profiled run silently serves past a stale artifact
        # and the recompile-and-reexport refresh never happens.
        from .plan_artifact import ArtifactStale

        try:
            validate(inputs, qparams)
        except ArtifactStale:
            prepared.recompile()
            inputs = prepared._inputs()
    seg = getattr(prepared, "_segmented", None)
    if seg is None or seg.stale(prepared):
        seg = prepared._segmented = SegmentedPlan(prepared)
    return seg.run(inputs, qparams)


def profile_eligible(prepared) -> bool:
    """Only plain single-chip PreparedPlans segment: chunked/grace-hash
    plans stream (their stages ARE the chunk loop), PX plans shard over
    the mesh — both keep the plan-level monitor row they have today."""
    return (hasattr(prepared, "run_device")
            and getattr(prepared, "plan", None) is not None
            and not getattr(prepared, "px_nsh", 0)
            and getattr(prepared, "params", None) is not None)


# ---- calibration record store ----------------------------------------------


@dataclass
class OperatorRecord:
    """Cumulative per-(digest, node_id, op_kind) calibration record.
    Counters only grow; window consumers (awr_report, the sentinel)
    diff last-first exactly like the host-tax registry rows."""

    digest: str
    node_id: int
    op_kind: str
    est_rows: int = 0
    plan_id: int = 0
    executions: int = 0
    device_us: float = 0.0
    build_us: float = 0.0
    probe_us: float = 0.0
    rows: int = 0
    out_bytes: int = 0
    work_rows: int = 0
    last_rows: int = 0
    last_device_us: float = 0.0
    max_miss: float = 1.0
    hist_us: list = field(default_factory=lambda: [0] * _NB)
    hist_rows: list = field(default_factory=lambda: [0] * _NB)
    hist_bytes: list = field(default_factory=lambda: [0] * _NB)

    @property
    def avg_rows(self) -> float:
        return self.rows / self.executions if self.executions else 0.0

    @property
    def miss(self) -> float:
        """(estimate, actual) calibration ratio over the record's
        lifetime average actual cardinality."""
        if not self.executions:
            return 1.0
        return miss_factor(self.est_rows, self.avg_rows)

    def fold(self, s: OpSample) -> None:
        self.executions += 1
        self.device_us += s.device_us
        self.build_us += s.build_us
        self.probe_us += s.probe_us
        self.rows += s.rows
        self.out_bytes += s.out_bytes
        self.work_rows += s.work_rows
        self.last_rows = s.rows
        self.last_device_us = s.device_us
        self.max_miss = max(self.max_miss,
                            miss_factor(self.est_rows, s.rows))
        self.hist_us[_bucket(s.device_us)] += 1
        self.hist_rows[_bucket(s.rows)] += 1
        self.hist_bytes[_bucket(s.out_bytes)] += 1

    def as_dict(self) -> dict:
        return {
            "digest": self.digest,
            "node_id": self.node_id,
            "op_kind": self.op_kind,
            "est_rows": self.est_rows,
            "plan_id": self.plan_id,
            "executions": self.executions,
            "device_us": self.device_us,
            "build_us": self.build_us,
            "probe_us": self.probe_us,
            "rows": self.rows,
            "out_bytes": self.out_bytes,
            "work_rows": self.work_rows,
            "last_rows": self.last_rows,
            "last_device_us": self.last_device_us,
            "avg_rows": self.avg_rows,
            "miss_factor": self.miss,
            "max_miss": self.max_miss,
            "hist_us": list(self.hist_us),
            "hist_rows": list(self.hist_rows),
            "hist_bytes": list(self.hist_bytes),
        }


class OperatorProfileStore:
    """Bounded per-digest store of operator calibration records.

    Keyed digest -> node_id; eviction is coldest-digest-first by fold
    sequence (the same policy the statement summary uses), bounded by
    ob_plan_profile_max_digests. snapshot() emits plain cumulative data
    the WorkloadRepository embeds per snapshot — every downstream
    consumer (awr, sentinel, obdiag) windows by diffing snapshots."""

    def __init__(self, max_digests: int = 128):
        self._lock = threading.Lock()
        # digest -> {"seq": last-fold seq, "nodes": {nid: OperatorRecord}}
        self._digests: dict[str, dict] = {}
        self.max_digests = max_digests
        self._seq = 0
        self.enabled = True
        self.profiles = 0
        self.evictions = 0

    def set_max_digests(self, n: int) -> None:
        with self._lock:
            self.max_digests = int(n)
            self._evict_locked()

    def _evict_locked(self) -> None:
        while len(self._digests) > max(self.max_digests, 1):
            cold = min(self._digests, key=lambda d: self._digests[d]["seq"])
            del self._digests[cold]
            self.evictions += 1

    def fold(self, digest: str, samples, est: dict | None,
             plan_id: int = 0) -> None:
        """Fold one profiled execution's samples under `digest`; `est`
        maps node_id -> compile-time estimated rows."""
        if not self.enabled or not samples:
            return
        est = est or {}
        with self._lock:
            self._seq += 1
            d = self._digests.get(digest)
            if d is None:
                d = self._digests[digest] = {"seq": self._seq, "nodes": {}}
                if len(self._digests) > max(self.max_digests, 1):
                    self._evict_locked()
            d["seq"] = self._seq
            self.profiles += 1
            nodes = d["nodes"]
            for s in samples:
                r = nodes.get(s.node_id)
                if r is None:
                    r = nodes[s.node_id] = OperatorRecord(
                        digest=digest, node_id=s.node_id,
                        op_kind=s.op_kind,
                        est_rows=int(est.get(s.node_id, 0)),
                        plan_id=plan_id,
                    )
                if plan_id:
                    r.plan_id = plan_id
                r.fold(s)

    def rows(self) -> list[dict]:
        """Flat per-operator rows (virtual-table surface), ordered by
        digest then node id."""
        with self._lock:
            out = []
            for digest in sorted(self._digests):
                nodes = self._digests[digest]["nodes"]
                for nid in sorted(nodes):
                    out.append(nodes[nid].as_dict())
            return out

    def digest_profile(self, digest: str) -> list[dict]:
        """One digest's operator records (flight-recorder bundles)."""
        with self._lock:
            d = self._digests.get(digest)
            if d is None:
                return []
            return [d["nodes"][n].as_dict() for n in sorted(d["nodes"])]

    def ann_route_rates(self) -> tuple[float, float] | None:
        """Measured (ivf_us_per_row, brute_us_per_row) for the ANN route
        decision, aggregated across every digest's VectorTopN /
        VecDistance records. None until BOTH routes have been profiled
        with tracked work — the optimizer then falls back to its flops
        model rather than cost against a one-sided measurement."""
        ivf_us = ivf_rows = brute_us = brute_rows = 0.0
        with self._lock:
            for d in self._digests.values():
                for r in d["nodes"].values():
                    if r.work_rows <= 0:
                        continue
                    if r.op_kind == "VectorTopN":
                        ivf_us += r.device_us
                        ivf_rows += r.work_rows
                    elif r.op_kind == "VecDistance":
                        brute_us += r.device_us
                        brute_rows += r.work_rows
        if ivf_rows <= 0 or brute_rows <= 0:
            return None
        return (ivf_us / ivf_rows, brute_us / brute_rows)

    def snapshot(self) -> dict:
        """Cumulative plain-data image for workload snapshots. Node ids
        are stringified so the image round-trips JSON identically."""
        with self._lock:
            return {
                "profiles": self.profiles,
                "evictions": self.evictions,
                "digests": {
                    digest: {
                        str(nid): d["nodes"][nid].as_dict()
                        for nid in d["nodes"]
                    }
                    for digest, d in self._digests.items()
                },
            }


# ---- sampling policy --------------------------------------------------------


class PlanProfiler:
    """Per-digest sampling policy + the statement-digest handoff.

    The server layer sets the pending digest (thread-local) before
    dispatch; the engine's _execute_entry takes it, asks decide(), and
    when a reason comes back runs the statement through run_profiled —
    serving the result FROM the profiled run, never executing twice.
    Forcing: EXPLAIN ANALYZE calls force_next(); the slow-query
    watermark calls mark_slow() so the NEXT occurrence of a slow digest
    carries an operator profile into its flight-recorder bundle."""

    def __init__(self, store: OperatorProfileStore | None = None,
                 sample_every: int = 64):
        self.store = store if store is not None else OperatorProfileStore()
        self.sample_every = sample_every
        self.enabled = True
        self._counts: dict[str, int] = {}
        self._force: set[str] = set()
        self._tls = threading.local()
        self._lock = threading.Lock()
        self.slow_marks = 0

    # -- per-statement digest handoff (server layer) --
    def set_pending(self, digest: str) -> None:
        self._tls.digest = digest

    def clear_pending(self) -> None:
        self._tls.digest = None

    def take_pending(self) -> str | None:
        return getattr(self._tls, "digest", None)

    # -- forcing --
    def force_next(self, digest: str) -> None:
        with self._lock:
            self._force.add(digest)

    def mark_slow(self, digest: str) -> None:
        self.slow_marks += 1
        self.force_next(digest)

    def wants_force(self, digest: str) -> bool:
        """Peek (no mutation): a pending forced profile needs a REAL
        execution, so the result cache must not serve this digest."""
        with self._lock:
            return digest in self._force

    def decide(self, digest: str) -> str | None:
        """Count one execution of `digest`; return the profiling reason
        ("forced" | "first" | "sample") or None. Deterministic — cadence
        is execution-count based, so tests drive it without a clock."""
        if not self.enabled or not self.store.enabled:
            return None
        with self._lock:
            if digest in self._force:
                self._force.discard(digest)
                self._counts[digest] = self._counts.get(digest, 0) + 1
                return "forced"
            n = self._counts.get(digest, 0)
            if len(self._counts) > 4 * max(self.store.max_digests, 1):
                # bounded alongside the store; a reset re-arms
                # first-recurrence sampling, which only over-profiles
                self._counts.clear()
                n = 0
            self._counts[digest] = n + 1
            if n == 1:
                # Profile the first RE-execution, not the very first run:
                # a digest must prove it recurs before paying a segmented
                # trace, so one-shot ad-hoc statements never see the
                # profiling compile cost.  EXPLAIN ANALYZE and the slow
                # watermark still force a profile on demand.
                return "first"
            se = self.sample_every
            if se > 0 and n > 1 and n % se == 0:
                return "sample"
            return None
