"""Device-memory governor: an HBM ledger with admission-time reservations.

Reference surface: ObTenantMemoryMgr / the 500-tenant memory chunks
(lib/alloc) on the OceanBase side, crossed with Tailwind's discipline of
treating accelerator memory as the scarce *managed* resource: every
statement states its peak device working set up front (measured per
digest by the workload repository, a conservative planner estimate for
cold digests) and the governor either grants a reservation, queues the
statement on the "device memory reservation" wait event, or rejects it
against the statement deadline. Nothing uploads to device unaccounted,
so resource exhaustion is a *planned-for, degradable* condition instead
of a process kill.

Two accounting axes share one ledger:

- a global device budget (config ``ob_device_memory_limit``; 0 = auto:
  a fraction of detected HBM, or a synthetic budget on CPU backends so
  the whole subsystem stays tier-1 testable), shrunk multiplicatively by
  ``note_oom()`` whenever a real/injected device OOM proves the
  estimates optimistic;
- per-tenant shares seeded from ``TenantUnit.memory_limit`` exactly the
  way admission slots are seeded from ``TenantUnit.max_workers``: a
  tenant's governor reservations + its resident catalog snapshot bytes
  are charged against the *same* limit, so a tenant at its memory limit
  queues instead of evicting a neighbour's residency.

The ledger must balance: every grant is released in a ``finally`` (the
Reservation is a context manager and release is idempotent), and
``ledger_balanced()`` is asserted by the reservation hammer test and at
chaos-scenario exit.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..share import gap_ledger as _gap

#: synthetic budget used when no accelerator reports its HBM size (CPU
#: tier-1 backend); big enough that tests opt *in* to pressure by
#: configuring a small explicit limit.
SYNTHETIC_CPU_BUDGET = 2 << 30

#: fraction of detected HBM handed to the governor when the config asks
#: for auto-sizing (the rest covers XLA scratch, compiled executables
#: and the resident block cache which are not reservation-tracked).
AUTO_HBM_FRACTION = 0.75

#: note_oom() multiplies the effective budget by this; floor below.
OOM_SHRINK = 0.75
OOM_SHRINK_FLOOR = 0.25

#: conservative planner-side bytes/row guess used when deriving a chunk
#: size from a byte budget (matches chunked.py's wide-row assumption).
_EST_ROW_BYTES = 128


def detect_device_budget() -> int:
    """Best-effort HBM detection: jax device memory_stats when the
    backend exposes it (TPU/GPU), else the synthetic CPU budget."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = getattr(dev, "memory_stats", None)
        if callable(stats):
            limit = (stats() or {}).get("bytes_limit", 0)
            if limit:
                return int(limit * AUTO_HBM_FRACTION)
    except Exception:
        pass
    return int(os.environ.get("OB_TPU_SYNTHETIC_HBM", SYNTHETIC_CPU_BUDGET))


def derive_chunk_rows(budget_bytes: int, default_rows: int,
                      row_bytes: int = _EST_ROW_BYTES) -> int:
    """Chunk size for a byte budget, clamped so a tiny budget still makes
    forward progress and a huge one keeps the default.

    `row_bytes` must be the DECODED on-device row width of the streamed
    columns (engine/pipeline.decoded_row_bytes), not the wire width: the
    governor charges staged (compressed) host-pinned bytes separately
    through the staged ledger, so sizing chunks from compressed bytes
    would let a high-ratio RLE column overcommit HBM by its encoding
    ratio. Callers without column knowledge keep the conservative
    wide-row default."""
    rows = int(max(budget_bytes, 1) // max(int(row_bytes), 1))
    return max(4096, min(default_rows, rows))


class Reservation:
    """One granted slice of the ledger. Idempotent release; usable as a
    context manager so error paths cannot leak bytes."""

    __slots__ = ("_gov", "tenant", "nbytes", "_live")

    def __init__(self, gov: "MemoryGovernor", tenant: str, nbytes: int):
        self._gov = gov
        self.tenant = tenant
        self.nbytes = nbytes
        self._live = True

    def release(self) -> None:
        if self._live:
            self._live = False
            self._gov._release(self.tenant, self.nbytes)

    def __enter__(self) -> "Reservation":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class StagedLease:
    """One staged (host-pinned, wire-encoded) chunk's slice of the staged
    ledger — the streaming prefetcher holds one per in-flight chunk.
    Idempotent release; usable as a context manager so a cancelled
    prefetch cannot leak staged bytes."""

    __slots__ = ("_gov", "tenant", "nbytes", "_live")

    def __init__(self, gov: "MemoryGovernor", tenant: str, nbytes: int):
        self._gov = gov
        self.tenant = tenant
        self.nbytes = nbytes
        self._live = True

    def release(self) -> None:
        if self._live:
            self._live = False
            self._gov._release_staged(self.nbytes)

    def __enter__(self) -> "StagedLease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


@dataclass
class _Tenant:
    limit: Optional[int]  # None = unlimited share
    resident_fn: Optional[Callable[[], int]]
    reserved: int = 0


class MemoryGovernor:
    """Per-device HBM ledger with per-tenant shares and a wait queue."""

    def __init__(self, budget: int, max_queue: int = 64,
                 clock: Optional[Callable[[], float]] = None):
        self.budget = int(budget)
        self.max_queue = max_queue
        self._shrink = 1.0
        self.reserved = 0
        self.peak_reserved = 0
        self._tenants: dict[str, _Tenant] = {}
        # sharded-residency reporters (mesh executors): each returns the
        # PER-DEVICE bytes its partitioned tables pin — total/n_shards,
        # because row sharding leaves each device one slice of every
        # resident table. The budget is per-device HBM, so this is the
        # unit that competes with reservations for the same pool.
        self._sharded_fns: list[Callable[[], int]] = []
        self._waiters = 0
        self._cond = threading.Condition()
        # staged ledger: host-pinned wire-encoded chunk buffers held by
        # the streaming prefetcher (engine/pipeline.py). Tracked apart
        # from device reservations — staged bytes live in HOST memory
        # awaiting H2D, so they must not eat the HBM pool — but they
        # participate in ledger_balanced(): a statement error/timeout
        # with a prefetch in flight must still drain to zero.
        self.staged = 0
        self.peak_staged = 0
        # monotonic counters (mirrored into sysstat by callers)
        self.grants = 0
        self.rejects = 0
        self.oom_notes = 0
        # bounded ring of recent reservation-wait seconds for the p99
        # surfaced in __all_virtual_memory_governor and the sentinel
        self._wait_ring: list[float] = []
        self._wait_cap = 512
        import time as _t

        self._clock = clock if clock is not None else _t.monotonic

    # ------------------------------------------------------------ config
    def set_budget(self, budget: int) -> None:
        with self._cond:
            self.budget = int(budget)
            self._cond.notify_all()

    def register_tenant(self, name: str, memory_limit: Optional[int],
                        resident_fn: Optional[Callable[[], int]] = None
                        ) -> None:
        """Seed a tenant share from its TenantUnit.memory_limit. The
        resident_fn reports the tenant's resident catalog snapshot bytes
        so reservations and residency charge one accounting surface."""
        with self._cond:
            t = self._tenants.get(name)
            if t is None:
                self._tenants[name] = _Tenant(memory_limit, resident_fn)
            else:  # re-register (restart): keep live reservation count
                t.limit = memory_limit
                if resident_fn is not None:
                    t.resident_fn = resident_fn

    def register_sharded_residency(self, fn: Callable[[], int]) -> None:
        """Register a mesh executor's partitioned-residency reporter
        (ShardedResidency.per_device_bytes). Idempotent per callable."""
        with self._cond:
            if fn not in self._sharded_fns:
                self._sharded_fns.append(fn)

    # ----------------------------------------------------------- budget
    def effective_budget(self) -> int:
        return max(1, int(self.budget * self._shrink))

    def sharded_resident_bytes(self) -> int:
        """Per-device bytes pinned by partitioned (mesh-sharded) tables
        across all registered mesh executors."""
        total = 0
        for fn in list(self._sharded_fns):
            try:
                total += int(fn())
            except Exception:
                pass
        return total

    def upload_budget(self) -> int:
        """What a single statement may plan to hold on device: the
        executor's prepare() consults this before a whole-table upload."""
        return self.effective_budget()

    def remaining(self) -> int:
        with self._cond:
            return max(0, self.effective_budget() - self.reserved
                       - self.sharded_resident_bytes())

    def note_oom(self) -> None:
        """A device OOM proved the estimates optimistic: shrink the
        reservation pool multiplicatively (ladder rung 1)."""
        with self._cond:
            self._shrink = max(OOM_SHRINK_FLOOR, self._shrink * OOM_SHRINK)
            self.oom_notes += 1

    def reset_shrink(self) -> None:
        with self._cond:
            self._shrink = 1.0
            self._cond.notify_all()

    # ------------------------------------------------------------ ledger
    def _tenant_fits(self, t: Optional[_Tenant], nbytes: int) -> bool:
        if t is None or t.limit is None:
            return True
        if t.reserved == 0:
            # a tenant's LONE statement is always admissible: its own
            # resident snapshots are reclaimable (server-side
            # _enforce_memory evicts the tenant's OWN coldest tables),
            # so an over-resident tenant degrades its own working set
            # instead of deadlocking at admission. What the limit gates
            # is concurrency: a second reservation must fit beside the
            # first AND the residency both charge the same quota.
            return True
        resident = 0
        if t.resident_fn is not None:
            try:
                resident = int(t.resident_fn())
            except Exception:
                resident = 0
        return t.reserved + resident + nbytes <= t.limit

    def reserve(self, tenant: str, nbytes: int,
                timeout_s: float = 5.0) -> Optional[Reservation]:
        """Grant `nbytes` against the ledger, waiting up to `timeout_s`.

        Returns None on timeout or queue-depth backpressure (the caller
        maps that onto DeviceMemoryTimeout / the statement deadline).
        A single statement larger than the whole effective budget is
        clamped to it: it must still run (degrading via the ladder),
        just strictly alone."""
        nbytes = int(max(0, nbytes))
        if nbytes == 0:
            return Reservation(self, tenant, 0)
        deadline = self._clock() + max(timeout_s, 0.0)
        with self._cond:
            t = self._tenants.get(tenant)
            waited = False
            t0 = self._clock()
            while True:
                # re-clamp every pass: note_oom() can shrink the pool
                # while we wait, and a request clamped to the OLD budget
                # would otherwise never fit again
                want = min(nbytes, self.effective_budget())
                if t is not None and t.limit is not None:
                    # a share-capped tenant's lone statement is likewise
                    # clamped so it can always eventually be admitted
                    want = min(want, max(1, t.limit))
                # sharded residency shrinks the pool new reservations
                # compete for — but never below `want`: a lone statement
                # must stay admissible even when partitioned tables pin
                # most of the device (they are evictable, exactly like
                # the per-tenant lone-statement rule), else admission
                # deadlocks with no one left to trigger eviction.
                pool = self.effective_budget()
                sharded = self.sharded_resident_bytes()
                if sharded:
                    pool = max(pool - sharded, want)
                fits = (self.reserved + want <= pool
                        and self._tenant_fits(t, want))
                if fits:
                    break
                if not waited and self._waiters >= self.max_queue:
                    self.rejects += 1  # queue-depth backpressure
                    return None
                rem = deadline - self._clock()
                if rem <= 0:
                    self.rejects += 1
                    self._note_wait(self._clock() - t0)
                    return None
                self._waiters += 1
                waited = True
                try:
                    self._cond.wait(timeout=min(rem, 0.05))
                finally:
                    self._waiters -= 1
            if waited:
                self._note_wait(self._clock() - t0)
            self.reserved += want
            self.peak_reserved = max(self.peak_reserved, self.reserved)
            if t is not None:
                t.reserved += want
            self.grants += 1
            return Reservation(self, tenant, want)

    def _release(self, tenant: str, nbytes: int) -> None:
        with self._cond:
            self.reserved = max(0, self.reserved - nbytes)
            t = self._tenants.get(tenant)
            if t is not None:
                t.reserved = max(0, t.reserved - nbytes)
            self._cond.notify_all()

    def stage(self, tenant: str, nbytes: int) -> StagedLease:
        """Charge `nbytes` of host-pinned staged (wire-encoded) chunk
        buffers to the staged ledger. Never blocks: the prefetch queue
        depth is the backpressure (at most `depth` staged chunks exist),
        so this is accounting + leak detection, not admission."""
        nbytes = int(max(0, nbytes))
        with self._cond:
            self.staged += nbytes
            self.peak_staged = max(self.peak_staged, self.staged)
        return StagedLease(self, tenant, nbytes)

    def _release_staged(self, nbytes: int) -> None:
        with self._cond:
            self.staged = max(0, self.staged - nbytes)
            self._cond.notify_all()

    def _note_wait(self, s: float) -> None:
        # caller holds _cond
        self._wait_ring.append(s)
        if len(self._wait_ring) > self._wait_cap:
            del self._wait_ring[: len(self._wait_ring) - self._wait_cap]
        # host-tax: admission waits park the statement's own thread here,
        # so the hint lands on its ledger without any plumbing
        led = _gap.current()
        if led is not None and s > 0.0:
            led.add("governor reserve", s)

    # ------------------------------------------------------- observation
    def wait_p99_s(self) -> float:
        with self._cond:
            ring = sorted(self._wait_ring)
        if not ring:
            return 0.0
        return ring[min(len(ring) - 1, int(len(ring) * 0.99))]

    def under_pressure(self) -> bool:
        """Cheap predicate for admission-side consumers (the statement
        batcher clamps batch size while the ledger is mostly spoken
        for, or waiters are queued)."""
        with self._cond:
            eff = self.effective_budget()
            return (self._waiters > 0
                    or self.reserved * 4 >= eff * 3
                    or self._shrink < 1.0)

    def ledger_balanced(self) -> bool:
        with self._cond:
            return (self.reserved == 0
                    and self.staged == 0
                    and all(t.reserved == 0 for t in self._tenants.values()))

    def stats(self) -> dict:
        with self._cond:
            return {
                "budget": self.budget,
                "effective_budget": self.effective_budget(),
                "reserved": self.reserved,
                "peak_reserved": self.peak_reserved,
                "staged": self.staged,
                "peak_staged": self.peak_staged,
                "waiters": self._waiters,
                "grants": self.grants,
                "rejects": self.rejects,
                "oom_notes": self.oom_notes,
                "sharded_resident": self.sharded_resident_bytes(),
                "shrink": round(self._shrink, 4),
                "wait_p99_s": self.wait_p99_s() if self._wait_ring else 0.0,
                "tenants": {
                    name: {"limit": t.limit, "reserved": t.reserved}
                    for name, t in self._tenants.items()
                },
            }


__all__ = [
    "MemoryGovernor", "Reservation", "StagedLease", "detect_device_budget",
    "derive_chunk_rows", "SYNTHETIC_CPU_BUDGET",
]
