"""Persistent compiled-plan artifacts: AOT export + warm-boot serving.

Reference surface: ObPlanCache keeps compiled plans only in memory —
a restarted observer re-optimizes every statement. On TPU the cached
artifact is an XLA executable whose trace + compile costs seconds, so a
rebooted node spends its first minutes compiling instead of serving
(exactly the host-side stall that kills accelerator utilization). This
module persists each compiled executable with `jax.export` (StableHLO
serialization), keyed by the plan-cache identity — normalized text,
parameter signature, baked literals, plan fingerprint, schema +
dictionary versions — plus the jax/jaxlib/backend version and device
topology. A warm boot rebuilds the plan cache from disk: ZERO engine
traces (Executor.compile never runs) for cached statements, and the
backend compile of the deserialized StableHLO hits the XLA persistent
compilation cache that lives next to the artifacts.

Layout under the store directory:

    index.json      ranking + byte accounting; exec counts are synced
                    from the workload repository's statement summaries
                    so the boot warm-load hydrates the HOTTEST digests
                    first under the byte budget
    <aid>.meta      pickled ArtifactMeta: logical plan, physical
                    capacities, cache-key parts, fast-tier registration
                    material, output prototype
    <aid>.x         serialized base executable (jax.export blob)
    <aid>.b<K>.x    pow2 batched-bucket variants (vmapped executables)
    xla/            XLA persistent compilation cache (backend compiles
                    of deserialized programs land here)

ColumnBatch is a custom pytree whose static aux (Schema, Dictionary)
jax.export cannot serialize, so artifacts ride a FLAT calling
convention: the export wrapper flattens (inputs, qparams) to positional
array leaves, and the loader rebuilds the output ColumnBatch from a
pickled prototype (column names + schema + dictionaries captured at
trace time). vmap over a deserialized call is unsupported, so each
batched bucket exports as its own program.

Every load path is load-or-compile: deserialization failure, version or
topology mismatch, schema bump (key mismatch) and input-shape drift
each bump a dedicated sysstat counter and fall back to a clean
recompile — a stale executable never runs. Loads time into the
"plan artifact load" wait event.
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import pickle
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..core.column import ColumnBatch


class ArtifactStale(Exception):
    """A warm executable's input signature no longer matches the live
    catalog (DML changed a table's device capacity, a leaf count moved).
    PreparedPlan.jit_call catches this and recompiles from the pickled
    logical plan — never a wrong answer, at worst one honest compile."""


def env_signature() -> dict:
    """The portability key of a compiled artifact: an executable is only
    as reusable as the stack that built it."""
    import jaxlib

    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
        "x64": bool(jax.config.jax_enable_x64),
    }


@dataclass
class ArtifactMeta:
    """Everything needed to rebuild a live plan-cache entry from disk
    with zero parsing/planning/tracing."""

    aid: str
    art_key: tuple  # (norm_key, sig, baked, fingerprint, extra, tag)
    tables: tuple
    env: dict
    plan: object  # pickled logical plan (recompile fallback retraces it)
    params: object  # PhysicalParams (derived specs cleared; re-detected)
    input_spec: list
    overflow_nodes: list
    in_avals: tuple  # ((shape, dtype), ...) per flat input leaf
    nslots: int  # packed qparam width (int64 lanes; vectors span several)
    out_proto: tuple  # (col_names, valid_names, schema, dicts)
    output_names: tuple
    dtypes: list
    fast: dict | None = None  # FastEntry kwargs (text-tier re-install)
    text_key: str | None = None
    px_nsh: int = 0
    # SPMD programs: the mesh geometry the shardings were lowered against
    # (mesh_signature), the compiled exchange layout (worker spans come
    # back warm), and the MeshPlan (collective counters come back warm).
    # A hydrating executor whose live mesh signature differs is REJECTED
    # — an AOT program must never run with another mesh's shardings.
    mesh_sig: tuple = ()
    px_exchanges: list | None = None
    mesh_plan: object = None
    # compile-time optimizer row estimates per node id: a warm-booted
    # plan must profile against the estimates it was COMPILED with, or
    # its (estimate, actual) calibration pairs drift with later stats
    node_estimates: dict | None = None


class _WarmExecutable:
    """A deserialized AOT executable standing in for PreparedPlan.jitted.
    Calls validate the flat input signature first; any drift raises
    ArtifactStale so the owner recompiles from its logical plan instead
    of feeding wrong-shaped buffers to a stale program."""

    __slots__ = ("_compiled", "_avals", "_proto")

    def __init__(self, compiled, avals, proto):
        self._compiled = compiled
        self._avals = avals
        self._proto = proto

    def validate(self, inputs, qparams):
        """Raise ArtifactStale on any input-signature drift. Exposed so
        paths that DON'T dispatch through __call__ — the operator
        profiler's segmented run traces fresh stages over whatever
        shapes arrive — can still detect a stale artifact and refresh
        it instead of silently serving past it forever."""
        leaves = jax.tree_util.tree_leaves((inputs, qparams))
        if len(leaves) != len(self._avals):
            raise ArtifactStale("input leaf count drift")
        for a, (shp, dt) in zip(leaves, self._avals):
            if tuple(jnp.shape(a)) != tuple(shp) \
                    or str(jnp.result_type(a)) != dt:
                raise ArtifactStale("input aval drift")
        return leaves

    def __call__(self, inputs, qparams):
        leaves = self.validate(inputs, qparams)
        out_leaves = self._compiled(*leaves)
        return rebuild_output(self._proto, out_leaves)


def rebuild_output(proto, out_leaves):
    """(ColumnBatch, ovf_vec) from the flat output leaves: unflatten
    against a prototype rebuilt from the pickled static parts (names,
    schema, dicts) — structurally identical to the treedef the export
    trace saw, since dict leaves flatten in sorted-key order."""
    col_names, valid_names, schema, dicts = proto
    shape = (
        ColumnBatch(
            cols=dict.fromkeys(col_names, 0),
            valid=dict.fromkeys(valid_names, 0),
            sel=0, nrows=0, schema=schema, dicts=dicts,
        ),
        0,
    )
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(shape), list(out_leaves))


def export_flat(fn, example):
    """Serialize `fn(inputs, qparams)` through jax.export over FLAT
    positional leaves (custom-pytree aux never reaches the serializer).
    Returns (blob, out_proto, in_avals); the output prototype is
    captured from the traced output's static attributes."""
    leaves, in_tree = jax.tree_util.tree_flatten(example)
    cell: dict = {}

    def _flat(*flat):
        inputs, qp = jax.tree_util.tree_unflatten(in_tree, list(flat))
        out, ovf = fn(inputs, qp)
        cell["proto"] = (
            tuple(sorted(out.cols)), tuple(sorted(out.valid)),
            out.schema, dict(out.dicts),
        )
        fl, _ = jax.tree_util.tree_flatten((out, ovf))
        return tuple(fl)

    from jax import export as jax_export

    specs = [jax.ShapeDtypeStruct(jnp.shape(a), jnp.result_type(a))
             for a in leaves]
    blob = jax_export.export(jax.jit(_flat))(*specs).serialize()
    avals = tuple(
        (tuple(jnp.shape(a)), str(jnp.result_type(a))) for a in leaves
    )
    return blob, cell["proto"], avals


def load_flat(blob, in_avals, proto, example_leaves=None):
    """Deserialize + AOT-compile an exported blob into a callable with
    the PreparedPlan.jitted signature. The backend compile of the
    StableHLO goes through jax's persistent compilation cache (pointed
    into the store directory), so a warm boot pays a disk read, not a
    compile. A multi-device (PX shard_map) program must lower against
    the live mesh shardings — carried by the freshly assembled input
    leaves — or jax rejects the single-device calling context."""
    from jax import export as jax_export

    exp = jax_export.deserialize(blob)
    multi = getattr(exp, "nr_devices", 1) > 1
    specs = []
    for i, (shp, dt) in enumerate(in_avals):
        sharding = None
        if multi and example_leaves is not None and i < len(example_leaves):
            sharding = getattr(example_leaves[i], "sharding", None)
        specs.append(
            jax.ShapeDtypeStruct(tuple(shp), jnp.dtype(dt),
                                 sharding=sharding))
    compiled = jax.jit(exp.call).lower(*specs).compile()
    return _WarmExecutable(compiled, in_avals, proto)


def _atomic_write(path: str, data: bytes) -> None:
    # artifacts are recomputable (worst case: one honest compile), so no
    # fsync — but they still ride the integrity envelope: a corrupt
    # artifact must be DETECTED and quarantined, never half-unpickled
    from ..storage.integrity import ARTIFACT, write_atomic

    write_atomic(path, data, fsync=False, path_class=ARTIFACT)


def _read_verified(path: str) -> bytes:
    """Verified read for every artifact file; raises FileNotFoundError
    (missing) or CorruptBlock (damaged) — never returns bad bytes."""
    from ..storage.integrity import ARTIFACT, read_verified

    return read_verified(path, path_class=ARTIFACT)


class PlanArtifactStore:
    """On-disk tier of the plan cache. Modes mirror the config parameter
    ob_plan_artifact_mode: "ro" hydrates but never writes, "rw" also
    exports on compile and re-exports on overflow recompile."""

    def __init__(self, root: str, mode: str = "rw",
                 max_bytes: int = 256 << 20, metrics=None):
        self.root = root
        self.mode = mode
        self.max_bytes = int(max_bytes)
        self.metrics = metrics
        self._lock = threading.RLock()
        os.makedirs(root, exist_ok=True)
        self._index: dict = {"env": env_signature(), "entries": {}}
        self._load_index()
        # per-entry runtime stats for __all_virtual_plan_artifact
        self.runtime: dict[str, dict] = {}
        self.miss_count = 0
        self._prime_pool = None
        self._enable_xla_cache()

    # ------------------------------------------------------------- state
    @property
    def readable(self) -> bool:
        return self.mode in ("ro", "rw")

    @property
    def writable(self) -> bool:
        return self.mode == "rw"

    def _note(self, name: str, n: float = 1) -> None:
        if self.metrics is not None:
            self.metrics.add(name, n)

    def _rt(self, aid: str) -> dict:
        st = self.runtime.get(aid)
        if st is None:
            st = self.runtime[aid] = {
                "hits": 0, "misses": 0, "load_us": 0, "warm": 0,
            }
        return st

    def _enable_xla_cache(self) -> None:
        """Point the process-global XLA persistent compilation cache into
        the store: backend compiles of deserialized programs (and of
        fresh compiles on this node) persist next to the artifacts."""
        try:
            jax.config.update(
                "jax_compilation_cache_dir", os.path.join(self.root, "xla"))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            try:
                jax.config.update(
                    "jax_persistent_cache_enable_xla_caches", "all")
            except Exception:
                pass  # knob spelling varies across jax versions
            # jax latches "no cache dir" on the first compile of the
            # process; without a reset the updates above are ignored
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass  # cache stays off; artifacts still skip the retrace

    # ----------------------------------------------------------- priming
    def _prime_async(self, blob, in_avals, proto, leaves) -> None:
        """Backend-compile the round-tripped export off the serving path.
        The deserialized program hashes differently from the original
        trace, so without this the FIRST warm boot still pays the XLA
        compile; priming writes the exact cache entry load_flat will
        look up, making every warm boot a disk read."""
        import concurrent.futures

        with self._lock:
            if self._prime_pool is None:
                self._prime_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix="plan-artifact-prime")
            pool = self._prime_pool

        def _job():
            try:
                load_flat(blob, in_avals, proto, example_leaves=leaves)
                self._note("plan artifact prime")
            except Exception:
                self._note("plan artifact prime error")
        try:
            pool.submit(_job)
        except RuntimeError:
            pass  # pool already shut down mid-close

    def drain(self) -> None:
        """Block until queued primes have hit the XLA cache (close path:
        the entry must be on disk before the next boot)."""
        with self._lock:
            pool, self._prime_pool = self._prime_pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    # ------------------------------------------------------------- index
    def _index_path(self) -> str:
        return os.path.join(self.root, "index.json")

    def _load_index(self) -> None:
        from ..storage.integrity import CorruptBlock, quarantine_file

        try:
            idx = json.loads(_read_verified(self._index_path()))
            if isinstance(idx, dict) and "entries" in idx:
                self._index = idx
        except FileNotFoundError:
            pass  # fresh store
        except CorruptBlock as e:
            # a corrupt index is quarantined and the store starts empty:
            # orphaned artifact files are unreachable (never hydrated)
            # and get re-exported/overwritten on the next compile
            quarantine_file(self._index_path(), e.reason)
            self._note("plan artifact quarantined")
            self._note("checksum failures")
        except (OSError, ValueError):
            pass

    def _save_index(self) -> None:
        if not self.writable:
            return
        try:
            _atomic_write(
                self._index_path(),
                json.dumps(self._index, sort_keys=True).encode())
        except OSError:
            pass

    def quarantine(self, aid: str, path: str, reason: str) -> None:
        """First load error on a corrupt artifact file: move it into
        quarantine/ (kept for forensics, never re-read), drop the whole
        entry from the index so later boots don't retry it, and count."""
        from ..storage.integrity import quarantine_file

        quarantine_file(path, reason)
        with self._lock:
            if aid in self._index["entries"]:
                if self.writable:
                    self._drop_files(aid)
                self._index["entries"].pop(aid, None)
                self._save_index()
        self._note("plan artifact quarantined")
        self._note("checksum failures")

    def key_id(self, art_key: tuple) -> str:
        return hashlib.md5(repr(art_key).encode()).hexdigest()

    def _paths(self, aid: str) -> tuple[str, str]:
        return (os.path.join(self.root, f"{aid}.meta"),
                os.path.join(self.root, f"{aid}.x"))

    def _bucket_path(self, aid: str, bucket: int) -> str:
        return os.path.join(self.root, f"{aid}.b{bucket}.x")

    def total_bytes(self) -> int:
        with self._lock:
            return sum(int(e.get("bytes", 0))
                       for e in self._index["entries"].values())

    def entries(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in self._index["entries"].items()}

    def ranked(self) -> list[tuple[str, dict]]:
        """(aid, index entry) hottest-first — the boot warm-load order.
        Exec counts come from the statement summaries synced at save /
        close time; ties break on save recency."""
        with self._lock:
            ents = list(self._index["entries"].items())
        ents.sort(key=lambda kv: (-int(kv[1].get("execs", 0)),
                                  -int(kv[1].get("seq", 0))))
        return ents

    def sync_exec_counts(self, summaries) -> None:
        """Fold the workload repository's per-digest exec counts into the
        ranking index (digest == the fast-tier text key)."""
        if not self.writable:
            return
        by_digest = {}
        try:
            for s in summaries:
                d = s.get("digest") if isinstance(s, dict) \
                    else getattr(s, "digest", None)
                n = s.get("exec_count") if isinstance(s, dict) \
                    else getattr(s, "exec_count", 0)
                if d:
                    by_digest[d] = int(n)
        except Exception:
            return
        with self._lock:
            for aid, ent in self._index["entries"].items():
                tk = ent.get("text")
                if tk in by_digest:
                    ent["execs"] = max(int(ent.get("execs", 0)),
                                       by_digest[tk])
            self._save_index()

    # -------------------------------------------------------------- save
    def _evict_to_budget(self, incoming: int) -> bool:
        """LRU-by-heat eviction so the store honors plan_artifact_max_bytes.
        Returns False when the incoming artifact alone exceeds the budget."""
        if incoming > self.max_bytes:
            self._note("plan artifact budget skip")
            return False
        ents = self._index["entries"]
        while ents and self.total_bytes() + incoming > self.max_bytes:
            coldest = min(
                ents, key=lambda k: (int(ents[k].get("execs", 0)),
                                     int(ents[k].get("seq", 0))))
            self._drop_files(coldest)
            ents.pop(coldest, None)
            self._note("plan artifact evict")
        return True

    def _drop_files(self, aid: str) -> None:
        meta_p, blob_p = self._paths(aid)
        ent = self._index["entries"].get(aid, {})
        for b in ent.get("buckets", ()):
            try:
                os.remove(self._bucket_path(aid, int(b)))
            except OSError:
                pass
        for p in (meta_p, blob_p):
            try:
                os.remove(p)
            except OSError:
                pass

    def save(self, art_key: tuple, prepared, *, output_names, dtypes,
             tables, fast: dict | None = None, text_key: str | None = None,
             execs: int = 1) -> str | None:
        """Export one freshly compiled plan. Returns the artifact id, or
        None when the plan is not exportable (legacy-tuple qparams,
        export/pickle failure) — the live entry is unaffected either way."""
        if not self.writable:
            return None
        spec = getattr(prepared, "_qparam_spec", None)
        if spec is None or not getattr(prepared, "_traceable", True):
            self._note("plan artifact export skip")
            return None
        aid = self.key_id(art_key)
        from .executor import packed_width

        try:
            inputs = prepared._inputs()
            qex = np.zeros(packed_width(spec), np.int64)
            blob, proto, avals = export_flat(prepared.jitted, (inputs, qex))
            params = copy.copy(prepared.params)
            params.clustered_aggs = {}
            params.vector_topns = {}
            meta = ArtifactMeta(
                aid=aid, art_key=art_key, tables=tuple(tables),
                env=env_signature(), plan=prepared.plan, params=params,
                input_spec=list(prepared.input_spec),
                overflow_nodes=list(prepared.overflow_nodes),
                in_avals=avals, nslots=packed_width(spec), out_proto=proto,
                output_names=tuple(output_names), dtypes=list(dtypes),
                fast=fast, text_key=text_key,
                px_nsh=int(getattr(prepared, "px_nsh", 0)),
                # save runs after the first successful execution, so the
                # lazily-traced exchange layout is populated by now
                mesh_sig=tuple(getattr(prepared, "mesh_sig", ()) or ()),
                px_exchanges=list(
                    getattr(prepared, "px_exchanges", None) or []),
                mesh_plan=getattr(prepared, "mesh_plan", None),
                node_estimates=dict(
                    getattr(prepared, "node_estimates", None) or {}),
            )
            meta_blob = pickle.dumps(meta, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            self._note("plan artifact export error")
            return None
        nbytes = len(blob) + len(meta_blob)
        with self._lock:
            if not self._evict_to_budget(nbytes):
                return None
            meta_p, blob_p = self._paths(aid)
            try:
                _atomic_write(meta_p, meta_blob)
                _atomic_write(blob_p, blob)
            except OSError:
                self._note("plan artifact export error")
                return None
            ents = self._index["entries"]
            old = ents.get(aid, {})
            ents[aid] = {
                "bytes": nbytes,
                "execs": max(int(old.get("execs", 0)), int(execs)),
                "seq": int(time.time() * 1e6),
                "text": text_key or (art_key[0] if art_key else ""),
                "buckets": [],
            }
            self._save_index()
        self._note("plan artifact save")
        self._note("plan artifact bytes saved", nbytes)
        prepared.artifact_ref = (self, aid)
        try:
            leaves = jax.tree_util.tree_flatten((inputs, qex))[0]
            self._prime_async(blob, avals, proto, leaves)
        except Exception:
            pass
        return aid

    def export_bucket(self, prepared, bucket: int, fn) -> None:
        """Persist one pow2 batched-bucket variant (vmap over a
        deserialized call is unsupported, so each bucket is its own
        exported program)."""
        if not self.writable:
            return
        ref = getattr(prepared, "artifact_ref", None)
        spec = getattr(prepared, "_qparam_spec", None)
        if ref is None or not spec:
            return
        aid = ref[1]
        from .executor import packed_width

        try:
            inputs = prepared._inputs()
            qb = np.zeros((bucket, packed_width(spec)), np.int64)
            blob, _proto, _avals = export_flat(fn, (inputs, qb))
        except Exception:
            self._note("plan artifact export error")
            return
        try:
            leaves = jax.tree_util.tree_flatten((inputs, qb))[0]
            self._prime_async(blob, _avals, _proto, leaves)
        except Exception:
            pass
        with self._lock:
            ent = self._index["entries"].get(aid)
            if ent is None:
                return
            try:
                _atomic_write(self._bucket_path(aid, bucket), blob)
            except OSError:
                return
            if bucket not in ent["buckets"]:
                ent["buckets"].append(int(bucket))
            ent["bytes"] = int(ent.get("bytes", 0)) + len(blob)
            self._save_index()
        self._note("plan artifact bucket save")

    def on_recompile(self, prepared) -> None:
        """Overflow recompile hook: the executable just changed capacity,
        so the on-disk artifact would replay the overflow on every boot.
        Re-export at the new capacity and drop the (stale) bucket
        variants."""
        ref = getattr(prepared, "artifact_ref", None)
        if ref is None or not self.writable:
            return
        aid = ref[1]
        with self._lock:
            ent = self._index["entries"].get(aid)
            if ent is None:
                prepared.artifact_ref = None
                return
            meta_p, _ = self._paths(aid)
            try:
                meta = pickle.loads(_read_verified(meta_p))
            except Exception:
                self._drop_files(aid)
                self._index["entries"].pop(aid, None)
                prepared.artifact_ref = None
                return
            for b in ent.get("buckets", ()):
                try:
                    os.remove(self._bucket_path(aid, int(b)))
                except OSError:
                    pass
            ent["buckets"] = []
        spec = getattr(prepared, "_qparam_spec", None) or ()
        from .executor import packed_width

        try:
            inputs = prepared._inputs()
            qex = np.zeros(packed_width(spec), np.int64)
            blob, proto, avals = export_flat(prepared.jitted, (inputs, qex))
            params = copy.copy(prepared.params)
            params.clustered_aggs = {}
            params.vector_topns = {}
            meta.params = params
            meta.input_spec = list(prepared.input_spec)
            meta.overflow_nodes = list(prepared.overflow_nodes)
            meta.in_avals = avals
            meta.out_proto = proto
            meta.node_estimates = dict(
                getattr(prepared, "node_estimates", None) or {})
            meta_blob = pickle.dumps(meta, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            self._note("plan artifact export error")
            return
        with self._lock:
            meta_p, blob_p = self._paths(aid)
            try:
                _atomic_write(meta_p, meta_blob)
                _atomic_write(blob_p, blob)
            except OSError:
                return
            ent = self._index["entries"].get(aid)
            if ent is not None:
                ent["bytes"] = len(blob) + len(meta_blob)
            self._save_index()
        self._note("plan artifact reexport")

    def load_bucket(self, prepared, bucket: int):
        """Hydrate one batched-bucket executable for a warm plan, or None
        (the caller recompiles — honestly counted — and rebuilds it)."""
        ref = getattr(prepared, "artifact_ref", None)
        proto = getattr(prepared, "_art_proto", None)
        spec = getattr(prepared, "_qparam_spec", None)
        if ref is None or proto is None or not self.readable or not spec:
            return None
        aid = ref[1]
        path = self._bucket_path(aid, bucket)
        t0 = time.perf_counter()
        try:
            from ..storage.integrity import CorruptBlock

            try:
                blob = _read_verified(path)
            except CorruptBlock as e:
                # quarantine just the bucket file; the base program and
                # the index entry stay (the caller recompiles the bucket)
                from ..storage.integrity import quarantine_file
                quarantine_file(path, e.reason)
                with self._lock:
                    ent = self._index["entries"].get(aid)
                    if ent is not None and bucket in ent.get("buckets", ()):
                        ent["buckets"].remove(bucket)
                        self._save_index()
                self._note("plan artifact quarantined")
                self._note("checksum failures")
                raise
            inputs = prepared._inputs()
            qb = np.zeros((bucket, len(spec)), np.int64)
            leaves = jax.tree_util.tree_leaves((inputs, qb))
            avals = tuple((tuple(jnp.shape(a)), str(jnp.result_type(a)))
                          for a in leaves)
            warm = load_flat(blob, avals, proto, example_leaves=leaves)
        except FileNotFoundError:
            return None
        except Exception:
            self._note("plan artifact load error")
            return None
        dt = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.wait("plan artifact load", dt)
        st = self._rt(aid)
        st["hits"] += 1
        st["load_us"] += int(dt * 1e6)
        self._note("plan artifact bucket hit")
        return warm

    # ----------------------------------------------------------- hydrate
    def read_meta(self, aid: str):
        """Pickled ArtifactMeta for one entry, or None (a corrupt file is
        quarantined on first load error; an unpicklable-but-valid-crc
        payload is counted as a load error)."""
        from ..storage.integrity import CorruptBlock

        if not self.readable:
            return None
        meta_p, _ = self._paths(aid)
        try:
            return pickle.loads(_read_verified(meta_p))
        except FileNotFoundError:
            return None
        except CorruptBlock as e:
            self.quarantine(aid, meta_p, e.reason)
            self._note("plan artifact load error")
            return None
        except Exception:
            self._note("plan artifact load error")
            return None

    def hydrate(self, aid: str, executor, key_extra_fn=None,
                preload_buckets: bool = True, meta=None):
        """Rebuild a live PreparedPlan from one artifact. Returns
        (meta, prepared) or None; every rejection bumps its own counter
        and the caller falls back to a clean compile. `key_extra_fn`
        (boot path) re-derives the schema/dict-version key material and
        rejects on mismatch — schema-bump invalidation semantics are
        identical to the in-memory tiers."""
        if not self.readable:
            return None
        with self._lock:
            known = aid in self._index["entries"]
        if not known:
            self.miss_count += 1
            self._note("plan artifact miss")
            return None
        t0 = time.perf_counter()
        st = self._rt(aid)
        _, blob_p = self._paths(aid)
        if meta is None:
            meta = self.read_meta(aid)
        if meta is None:
            st["misses"] += 1
            self._note("plan artifact load error")
            return None
        if meta.env != env_signature():
            st["misses"] += 1
            self._note("plan artifact version mismatch")
            return None
        if meta.px_nsh:
            # SPMD program: its shardings were lowered against one mesh
            # geometry. A different live mesh must key-mismatch cleanly
            # (counted; caller recompiles) — never run wrong shardings.
            saved_sig = tuple(getattr(meta, "mesh_sig", ()) or ())
            live_sig = tuple(getattr(executor, "mesh_sig", ()) or ())
            if saved_sig and saved_sig != live_sig:
                st["misses"] += 1
                self._note("plan artifact mesh mismatch")
                return None
        if key_extra_fn is not None:
            try:
                extra = key_extra_fn(meta.tables)
            except Exception:
                extra = None
            if extra != meta.art_key[4]:
                st["misses"] += 1
                self._note("plan artifact key mismatch")
                return None
        try:
            from ..storage.integrity import CorruptBlock

            try:
                blob = _read_verified(blob_p)
            except CorruptBlock as e:
                self.quarantine(aid, blob_p, e.reason)
                raise
            from .executor import PreparedPlan

            prepared = PreparedPlan(
                executor, meta.plan, meta.params, None,
                meta.input_spec, meta.overflow_nodes)
            # assemble + validate inputs BEFORE trusting the executable:
            # a table whose device capacity moved since export must fall
            # back to a compile, not feed a stale program
            inputs = prepared._inputs()
            leaves = jax.tree_util.tree_leaves(
                (inputs, np.zeros(meta.nslots, np.int64)))
            if len(leaves) != len(meta.in_avals) or any(
                tuple(jnp.shape(a)) != tuple(shp)
                or str(jnp.result_type(a)) != dt
                for a, (shp, dt) in zip(leaves, meta.in_avals)
            ):
                st["misses"] += 1
                self._note("plan artifact input mismatch")
                return None
            warm = load_flat(blob, meta.in_avals, meta.out_proto,
                             example_leaves=leaves)
        except Exception:
            st["misses"] += 1
            self._note("plan artifact load error")
            return None
        prepared.jitted = warm
        prepared._traceable = False
        prepared.artifact_ref = (self, aid)
        prepared._art_proto = meta.out_proto
        prepared.node_estimates = dict(
            getattr(meta, "node_estimates", None) or {})
        if meta.px_nsh:
            prepared.px_nsh = meta.px_nsh
            # the exchange layout and mesh plan were captured at save
            # time (post-trace): warm boots get their worker spans and
            # collective counters without ever re-tracing
            prepared.px_exchanges = list(
                getattr(meta, "px_exchanges", None) or [])
            prepared.mesh_sig = tuple(getattr(meta, "mesh_sig", ()) or ())
            mp = getattr(meta, "mesh_plan", None)
            if mp is not None:
                prepared.mesh_plan = mp
        dt = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.wait("plan artifact load", dt)
        st["hits"] += 1
        st["load_us"] += int(dt * 1e6)
        st["warm"] = 1
        self._note("plan artifact hit")
        if preload_buckets:
            with self._lock:
                buckets = list(self._index["entries"]
                               .get(aid, {}).get("buckets", ()))
            for b in buckets:
                fn = self.load_bucket(prepared, int(b))
                if fn is not None:
                    prepared._batched[int(b)] = fn
        return meta, prepared

    # ------------------------------------------------------------- flush
    def flush(self) -> None:
        """The plan cache's flush covers this tier too: schema/privilege
        driven invalidation must not leave executables that hydrate
        back. rw deletes the files; ro (can't write) just forgets the
        index so every hydration misses."""
        with self._lock:
            if self.writable:
                for aid in list(self._index["entries"]):
                    self._drop_files(aid)
            self._index["entries"] = {}
            self.runtime.clear()
            self._save_index()
        self._note("plan artifact flush")

    def census(self) -> list[dict]:
        """Per-entry rows for __all_virtual_plan_artifact: identity,
        bytes, ranking execs, bucket variants, and this boot's
        hit/miss/load-time tallies."""
        with self._lock:
            ents = {k: dict(v) for k, v in self._index["entries"].items()}
            rts = {k: dict(v) for k, v in self.runtime.items()}
        out = []
        for aid, ent in ents.items():
            st = rts.get(aid, {})
            out.append({
                "artifact_id": aid,
                "statement": str(ent.get("text", ""))[:128],
                "bytes": int(ent.get("bytes", 0)),
                "execs": int(ent.get("execs", 0)),
                "buckets": tuple(int(b) for b in ent.get("buckets", ())),
                "hits": int(st.get("hits", 0)),
                "misses": int(st.get("misses", 0)),
                "load_us": int(st.get("load_us", 0)),
                "warm": int(st.get("warm", 0)),
            })
        out.sort(key=lambda r: (-r["execs"], r["artifact_id"]))
        return out
