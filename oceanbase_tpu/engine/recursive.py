"""WITH RECURSIVE: host-driven fixpoint over jitted iteration steps.

Reference surface: src/sql/engine/recursive_cte — ObRecursiveUnionAllOp
drives a fake-CTE-table pump: execute the left (base) branch, feed each
produced batch back through the right (recursive) branch until empty.

The TPU translation keeps the data-dependent LOOP on the host (XLA traces
once; an unbounded data-dependent iteration cannot live inside one
program) while every ITERATION is a full jitted plan: the working table
materializes as a catalog temp table between rounds, so the step query
compiles once per capacity bucket (table capacities round to 1024s; jax
retraces only when the bucket grows) and rides the plan cache like any
other statement. UNION dedups each delta against everything seen (the
reference's breadth-first semantics); UNION ALL stops on an empty delta.
A bounded iteration count guards non-terminating recursion exactly like
the reference's cte_max_recursion_depth.
"""

from __future__ import annotations

import dataclasses
import itertools

import numpy as np

from ..core.table import Table
from ..sql import ast as A

MAX_ITERS = 200

_tmp_ids = itertools.count()


def recursive_cte_of(ast) -> str | None:
    """The single self-referencing CTE name, or None. Requires the
    RECURSIVE keyword: per standard scoping, a plain WITH whose body
    mentions its own name refers to the CATALOG table of that name, not
    itself. Multiple recursive CTEs raise (one per statement, like the
    reference)."""
    ctes = getattr(ast, "ctes", ())
    declared = set(getattr(ast, "recursive_ctes", ()) or ())
    if not ctes or not declared:
        return None
    rec = [
        name for name, body in ctes
        if name in declared and name in _table_refs(body)
    ]
    if len(rec) > 1:
        raise ValueError("only one recursive CTE per statement is supported")
    return rec[0] if rec else None


def _table_refs(node, out=None) -> set:
    if out is None:
        out = set()
    if isinstance(node, A.TableRef):
        out.add(node.name)
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            _table_refs(getattr(node, f.name), out)
    elif isinstance(node, (tuple, list)):
        for x in node:
            _table_refs(x, out)
    return out


def _rename_table(node, old: str, new: str):
    """Rewrite TableRef(old) -> TableRef(new, alias=old-or-explicit)."""
    if isinstance(node, A.TableRef) and node.name == old:
        return A.TableRef(new, node.alias or old)
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = _rename_table(v, old, new)
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(node, **changes) if changes else node
    if isinstance(node, tuple):
        items = tuple(_rename_table(x, old, new) for x in node)
        return items if any(a is not b for a, b in zip(items, node)) else node
    return node


from ..core.column import batch_rows_storage as _batch_rows_storage  # noqa: E402


def run_recursive(session, ast):
    """Execute a statement whose WITH contains one recursive CTE.

    Returns (out_batch, output_names). The caller (Session.run_ast)
    converts to a ResultSet."""
    from ..sql.logical import output_schema

    name = recursive_cte_of(ast)
    assert name is not None
    body = dict(ast.ctes)[name]
    if not (isinstance(body, A.SetSelect) and body.kind == "union"):
        raise ValueError(
            "recursive CTE body must be <base> UNION [ALL] <step>"
        )
    base_ast, step_ast = body.left, body.right
    if name in _table_refs(base_ast):
        raise ValueError("recursive CTE base branch must not self-reference")
    dedup = not body.all
    other_ctes = tuple((n, b) for n, b in ast.ctes if n != name)
    tmp = f"#rcte{next(_tmp_ids)}:{name}"

    def with_ctes(sel):
        return dataclasses.replace(
            sel, ctes=other_ctes, recursive_ctes=()
        ) if isinstance(sel, (A.Select, A.SetSelect)) else sel

    # ---- base branch -------------------------------------------------
    planned = session.planner.plan(with_ctes(base_ast))
    schema_src = output_schema(planned.plan)
    out_batch = session.executor.execute(planned.plan)
    names = list(planned.output_names)
    acc = _batch_rows_storage(out_batch, names)
    dicts = {n: out_batch.dicts[n] for n in names if n in out_batch.dicts}
    from ..core.column import renamed_storage_schema

    tmp_schema = renamed_storage_schema(schema_src, names)

    seen = None
    if dedup:
        seen = set(map(tuple, zip(*(acc[n] for n in names)))) \
            if names else set()
        # base dedups against itself too (UNION semantics)
        if acc and len(next(iter(acc.values()))) != len(seen):
            keep, s2 = [], set()
            for i, row in enumerate(zip(*(acc[n] for n in names))):
                if row not in s2:
                    s2.add(row)
                    keep.append(i)
            acc = {n: acc[n][keep] for n in names}

    frontier = acc

    def install(rows):
        session.catalog[tmp] = Table(tmp, tmp_schema, dict(rows), dict(dicts))
        session.executor.invalidate_table(tmp)
        session.stats.invalidate(tmp)

    step_renamed = _rename_table(with_ctes(step_ast), name, tmp)
    try:
        for it in range(MAX_ITERS):
            if len(next(iter(frontier.values()), ())) == 0:
                break
            install(frontier)
            sp = session.planner.plan(step_renamed)
            delta_b = session.executor.execute(sp.plan)
            delta = _batch_rows_storage(delta_b, list(sp.output_names))
            # align step output column names to the cte's
            delta = {n: delta[sn] for n, sn in zip(names, sp.output_names)}
            for n in names:
                if n in delta_b.dicts and n not in dicts:
                    dicts[n] = delta_b.dicts[n]
            if dedup:
                keep = []
                for i, row in enumerate(zip(*(delta[n] for n in names))):
                    if row not in seen:
                        seen.add(row)
                        keep.append(i)
                delta = {n: delta[n][keep] for n in names}
            if len(next(iter(delta.values()), ())) == 0:
                break
            acc = {n: np.concatenate([acc[n], delta[n]]) for n in names}
            frontier = delta
        else:
            raise RuntimeError(
                f"recursive CTE {name!r} exceeded {MAX_ITERS} iterations"
            )
        # ---- outer query over the materialized cte -------------------
        install(acc)
        outer = _rename_table(with_ctes(ast), name, tmp)
        planned = session.planner.plan(outer)
        out = session.executor.execute(planned.plan)
        return out, planned.output_names
    finally:
        session.catalog.pop(tmp, None)
        session.executor.invalidate_table(tmp)
        session.stats.invalidate(tmp)
