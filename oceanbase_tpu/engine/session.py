"""Session facade: SQL text in, result rows out, with a plan cache.

Reference surface: ObSql::stmt_query + ObPlanCache
(src/sql/ob_sql.cpp:153, src/sql/plan_cache/ob_plan_cache.h:227). The cache
key is the literal-normalized SQL text (fast-parser analog,
sql/parser.py normalize_for_cache); a hit reuses the compiled jitted
program — the expensive artifact on TPU is the XLA executable, so the plan
cache IS the compile cache.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.column import batch_to_host
from ..core.table import Table
from ..sql import parser as P
from ..sql.planner import Planner
from .executor import Executor


@dataclass
class ResultSet:
    names: tuple[str, ...]
    columns: dict[str, object]  # name -> np.ndarray | list

    @property
    def nrows(self) -> int:
        if not self.names:
            return 0
        c = self.columns[self.names[0]]
        return len(c)

    def rows(self) -> list[tuple]:
        cols = [self.columns[n] for n in self.names]
        return list(zip(*cols)) if cols else []


class Session:
    def __init__(self, catalog: dict[str, Table], unique_keys=None):
        self.catalog = catalog
        self.planner = Planner(catalog)
        self.executor = Executor(catalog, unique_keys=unique_keys)
        self._plan_cache: dict[str, tuple] = {}

    def sql(self, text: str) -> ResultSet:
        key, _params = P.normalize_for_cache(text)
        cached = self._plan_cache.get(key)
        if cached is None or cached[0] != text:
            # (round-1 cache: exact text only; parameterized plans replace
            # this once the executor takes literals as runtime args)
            ast = P.parse(text)
            planned = self.planner.plan(ast)
            prepared = self.executor.prepare(planned.plan)
            cached = (text, planned, prepared)
            self._plan_cache[key] = cached
        _, planned, prepared = cached
        out_batch = prepared.run()
        host = batch_to_host(out_batch)
        # order columns per select list
        cols = {n: host[n] for n in planned.output_names}
        return ResultSet(planned.output_names, cols)
