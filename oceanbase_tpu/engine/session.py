"""Session facade: SQL text in, result rows out, with a plan cache.

Reference surface: ObSql::stmt_query + ObPlanCache
(src/sql/ob_sql.cpp:153, src/sql/plan_cache/ob_plan_cache.h:227). The cache
key is the literal-normalized SQL text (fast-parser analog,
sql/parser.py normalize_for_cache); a hit reuses the compiled jitted
program — the expensive artifact on TPU is the XLA executable, so the plan
cache IS the compile cache.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass

import numpy as np

from ..core.column import batch_to_host
from ..core.table import Table
from ..sql import parser as P
from ..sql.plan_cache import (
    CacheEntry,
    FastEntry,
    PlanCache,
    bind,
    build_slot_map,
    parameterize,
    plan_fingerprint,
)
from ..sql.planner import Planner
from .executor import Executor


@dataclass
class ResultSet:
    names: tuple[str, ...]
    columns: dict[str, object]  # name -> np.ndarray | list
    affected: int = 0  # DML-affected row count (0 for queries)
    plan_cache_hit: bool = False  # this statement reused a compiled plan
    fast_path_hit: bool = False  # served by the text-keyed fast tier

    @property
    def nrows(self) -> int:
        if not self.names:
            return 0
        c = self.columns[self.names[0]]
        return len(c)

    def rows(self, limit: int | None = None) -> list[tuple]:
        cols = [self.columns[n] for n in self.names]
        out = list(zip(*cols)) if cols else []
        return out[:limit] if limit is not None else out


class LazyResultSet:
    """Device-resident ResultSet: same read surface as ResultSet, but
    column data stays on the TPU behind a DeviceResult cursor until a
    host access touches it. `nrows` costs two scalars (the async-dispatch
    sync point — overflow redrive happens there); `.columns` fetches
    everything once; `column(name)` transfers only that column;
    `rows(limit=k)` transfers only k compacted rows per column."""

    def __init__(self, names: tuple[str, ...], cursor, affected: int = 0,
                 plan_cache_hit: bool = False, fast_path_hit: bool = False):
        self.names = names
        self.affected = affected
        self.plan_cache_hit = plan_cache_hit
        self.fast_path_hit = fast_path_hit
        self._cursor = cursor
        self._columns_cache: dict | None = None
        self._nrows: int | None = None

    @property
    def nrows(self) -> int:
        # memoized: the completion path reads nrows several times per
        # statement (engine sync force, audit record, summary fold) and
        # each uncached read walks two property hops into the cursor
        n = self._nrows
        if n is None:
            n = self._nrows = self._cursor.nrows if self.names else 0
        return n

    @property
    def columns(self) -> dict[str, object]:
        # memoized: callers index rs.columns[...] in per-row loops, and
        # host_rows decode must not re-run per access
        if self._columns_cache is None:
            host = self._cursor.fetch_columns()
            self._columns_cache = {n: host[n] for n in self.names}
        return self._columns_cache

    def column(self, name: str):
        """One column's host values — transfers only this column (plus
        the shared sel mask once)."""
        return self._cursor.fetch_columns((name,))[name]

    def rows(self, limit: int | None = None) -> list[tuple]:
        if limit is not None:
            host = self._cursor.fetch_head(limit)
        else:
            host = self._cursor.fetch_columns()
        cols = [host[n] for n in self.names]
        return list(zip(*cols)) if cols else []


# fast_execute's "caller did not probe the result cache" marker (None is
# a real probe outcome: probed, uncacheable)
_RC_UNSET = object()


@dataclass
class _FastHit:
    """A resolved fast-tier lookup: the text entry, the re-bound slot
    values for THIS statement's literals, and the logical entry holding
    the compiled executable."""

    text_key: str
    fe: FastEntry
    values: list
    entry: CacheEntry
    # logical cache key of the entry (embeds schema/dict versions via
    # key_extra) — the result cache reuses it as its identity base
    key: tuple | None = None


class Session:
    def __init__(self, catalog: dict[str, Table], unique_keys=None,
                 plan_cache: PlanCache | None = None, key_extra_fn=None,
                 cache_enabled_fn=None, plan_monitor=None, views=None,
                 metrics=None, tracer=None, profile_enabled_fn=None):
        self.catalog = catalog
        from ..share.stats import StatsManager

        self.stats = StatsManager(catalog)
        self.planner = Planner(
            catalog, stats=self.stats, unique_keys=unique_keys, views=views
        )
        self.executor = Executor(
            catalog, unique_keys=unique_keys, stats=self.stats
        )
        # shareable across sessions (the reference's cache is per-tenant,
        # not per-session: ob_plan_cache.h:227)
        self.plan_cache = plan_cache if plan_cache is not None else PlanCache()
        # hook: extra cache-key material per referenced table set (the
        # DML-backed catalog keys entries on table dict versions, since
        # string literals bake dictionary lookups at trace time)
        self.key_extra_fn = key_extra_fn
        # hook: ob_enable_plan_cache (a disabled cache compiles every time)
        self.cache_enabled_fn = cache_enabled_fn
        # hook: server/diag.PlanMonitor (per-plan compile/exec stats)
        self.plan_monitor = plan_monitor
        # hook: share/metrics.MetricsRegistry (phase histograms + counters)
        self.metrics = metrics
        # hook: server/diag.Tracer — PX executions stitch per-DFO worker
        # spans into the active statement's trace through it
        self.tracer = tracer
        # hook: config enable_query_profile (None = always profile)
        self.profile_enabled_fn = profile_enabled_fn
        # hook: server/workload.TableAccessStats — per-execution fold of
        # the prepared plan's precomputed table/column access profile
        self.access = None
        # hook: share/timeline.ServingTimeline — per-dispatch device-busy
        # and compile-interference feed (the server wires it)
        self.timeline = None
        # per-statement phase breakdown of the LAST run_ast call (EXPLAIN
        # ANALYZE reads it right after executing the analyzed statement)
        self.last_phases: dict = {}
        # per-statement TPU resource attribution (server/diag.QueryProfile)
        # of the LAST run_ast call; None when profiling is off or the
        # statement bypassed run_ast (pure DDL)
        self.last_profile = None
        # logical plan of the LAST run_ast call (flight-recorder bundles
        # capture its repr as the plan text)
        self.last_plan = None
        # hook: engine/plan_profile.PlanProfiler — sampled per-operator
        # profiled execution (the server wires it and sets the pending
        # statement digest before dispatch)
        self.plan_profiler = None
        # whole-statement fusion knobs (server wires them to
        # ob_enable_result_narrow / ob_result_narrow_rows /
        # ob_result_narrow_max_rows): fuse the final result-frame gather
        # into the plan's device program so a warm statement is ONE
        # dispatch + ONE host roundtrip
        self.narrow_enabled_fn = None
        self.narrow_default_rows = 256
        self.narrow_max_rows = 4096
        # hook: engine/result_cache.ResultCache — device-resident narrowed
        # results keyed (logical key, bound literals, snapshot watermark);
        # a hit skips dispatch entirely
        self.result_cache = None
        # hook: tables -> snapshot watermark tuple (the server supplies
        # per-table committed data versions; staleness = key mismatch)
        self.result_watermark_fn = None
        # per-operator profile of the LAST profiled run_ast call (EXPLAIN
        # ANALYZE reads it to annotate the plan tree); None when the
        # statement was not profiled
        self.last_op_profile = None

    def materialize(self, text: str, name: str) -> Table:
        """Run a SELECT and materialize its result as a storage-domain
        Table (exact round-trip: decimals stay scaled ints, dates stay
        day numbers, NULLs keep their validity masks) — the engine half
        of materialized views."""
        from ..core.column import (
            batch_rows_storage,
            batch_valid_storage,
            renamed_storage_schema,
        )
        from ..sql.logical import output_schema
        from .recursive import recursive_cte_of, run_recursive

        ast = P.parse(text)
        if getattr(ast, "ctes", None) and recursive_cte_of(ast) is not None:
            batch, out_names = run_recursive(self, ast)
            names = list(out_names)
            schema_src = batch.schema
        else:
            planned = self.planner.plan(ast)
            schema_src = output_schema(planned.plan)
            batch = self.executor.execute(planned.plan)
            names = list(planned.output_names)
        valid = batch_valid_storage(batch, names)
        schema = renamed_storage_schema(schema_src, names)
        if valid:
            # a validity mask forces the field nullable, or make_batch
            # would drop the mask on the next read
            from dataclasses import replace as _rp

            from ..core.dtypes import Field as _F, Schema as _S

            schema = _S(tuple(
                _F(f.name, _rp(f.dtype, nullable=True))
                if f.name in valid else f
                for f in schema.fields
            ))
        return Table(
            name,
            schema,
            batch_rows_storage(batch, names),
            {n: batch.dicts[n] for n in names if n in batch.dicts},
            valid,
        )

    def sql(self, text: str) -> ResultSet:
        # fast-parser front end: one tokenize pass both normalizes the
        # text-tier key and extracts the literal tokens. A warm repeat
        # skips parse + resolve + rewrite + plan + parameterize entirely
        # and goes straight to binding the cached executable.
        t0 = time.perf_counter()
        fkey, params, kinds = P.fast_normalize(text)
        use_cache = self.cache_enabled_fn() if self.cache_enabled_fn else True
        if use_cache:
            hit = self.fast_lookup(fkey, params)
            if hit is not None:
                return self.fast_execute(
                    hit, fastparse_s=time.perf_counter() - t0)
        fastparse_s = time.perf_counter() - t0
        # the plain plan-cache key is the fast key with kind markers
        # collapsed (the tokenizer never emits a bare '?')
        norm_key = fkey.replace("?n", "?").replace("?s", "?")
        ast = P.parse(text)
        return self.run_ast(
            ast, norm_key,
            fast_reg=(fkey, params, kinds) if use_cache else None,
            fastparse_s=fastparse_s,
        )

    def fast_lookup(self, text_key: str, params: tuple, fe=None,
                    defer_adds=None):
        """Text-tier lookup + literal re-bind + logical-tier fetch.
        Returns a _FastHit ready for fast_execute, or None (counted as a
        fast miss) when any stage rejects: unknown text, a baked token
        changed, a converter refused the new literal (dtype widening), or
        the logical entry is gone (evicted / flushed / schema version
        moved the key_extra) — that last case also drops the text entry.
        Callers that already peeked the text tier (the server fast path
        peeks to run privilege checks first) pass the FastEntry via `fe`
        so the lookup isn't paid twice per statement; `defer_adds` is
        forwarded to fast_hit_get (statement-end counter batching)."""
        pc = self.plan_cache
        if fe is None:
            fe = pc.fast_peek(text_key)
            if fe is None:
                pc.note_fast_miss()
                return None
        vals = fe.bind_tokens(params)
        if vals is None:
            pc.note_fast_miss()
            return None
        extra = (self.key_extra_fn(fe.tables)
                 if self.key_extra_fn is not None else ())
        key = (id(self.catalog), fe.norm_key, fe.sig, fe.baked,
               fe.fingerprint, extra)
        entry = pc.fast_hit_get(key, defer_adds=defer_adds)
        if entry is None:
            pc.fast_invalidate(text_key)
            pc.note_fast_miss()
            return None
        return _FastHit(text_key, fe, vals, entry, key)

    def result_cache_key(self, hit: "_FastHit"):
        """Result-cache identity for a fast hit, or None when the
        statement is uncacheable (not a SELECT, cache off, unhashable
        literal values). The key embeds the logical entry key (schema +
        dictionary versions ride key_extra) plus the bound literals and
        the referenced tables' snapshot watermark — any committed DML,
        schema bump or dict growth changes the key instead of serving a
        stale frame."""
        rc = self.result_cache
        if rc is None or not rc.enabled() or hit.key is None:
            return None
        if getattr(hit.fe, "stmt_type", None) != "Select":
            return None
        wm = (self.result_watermark_fn(hit.fe.tables)
              if self.result_watermark_fn is not None else ())
        # long string literals (query embeddings — a 128-d vector is a
        # ~1.4KB bracket text) key by digest: an exact-text collision is
        # a SHA-256 collision, and the key stays a few dozen bytes
        vals = tuple(
            hashlib.sha256(v.encode()).digest()
            if type(v) is str and len(v) > 256 else v
            for v in hit.values
        )
        return (hit.key, vals, wm)

    def result_cache_probe(self, hit: "_FastHit", rc_key,
                           fastparse_s: float = 0.0):
        """Serve a fast hit from the device-resident result cache, or
        None on miss. A hit skips bind + dispatch + sync entirely and
        still fills last_phases/last_profile so completion accounting
        (audit, summary, host-tax ledger) sees a normal statement."""
        rc = self.result_cache
        if rc is None or rc_key is None:
            return None
        ce = rc.get(rc_key)
        if ce is None:
            return None
        rs = ResultSet(ce.names, ce.copy_columns(), plan_cache_hit=True,
                       fast_path_hit=True)
        phases = {
            "plan_s": 0.0, "compile_s": 0.0, "fastparse_s": fastparse_s,
            "bind_s": 0.0, "dispatch_s": 0.0, "fetch_s": 0.0,
            "exec_s": 0.0, "rows": rs.nrows, "cache_hit": True,
            "fast_hit": True, "result_cache": True,
        }
        self.last_phases = phases
        profile = None
        if self.profile_enabled_fn is None or self.profile_enabled_fn():
            from ..server.diag import QueryProfile

            profile = QueryProfile(
                compile_hit=True, fastparse_s=fastparse_s,
                fast_path_hit=True)
        self.last_profile = profile
        self.last_plan = getattr(hit.entry.prepared, "plan", None)
        self.last_op_profile = None
        m = self.metrics
        if m is not None and m.enabled:
            m.add("result rows returned", rs.nrows)
            vts = getattr(
                getattr(hit.entry.prepared, "params", None),
                "vector_topns", None)
            if vts:
                # an ANN statement served straight from the device-
                # resident cache: the whole probe+re-rank was skipped
                m.add("ann cache hits")
        # a cached serve is still logically a read of its tables: fold
        # the plan's access profile so advisor heat (projection
        # keep/drop, index recommendations) doesn't see a dashboard
        # table go cold the moment its statements start hitting
        acc = self.access
        if acc is not None and acc.enabled:
            prepared = hit.entry.prepared
            memo = getattr(prepared, "_access_memo", None)
            if memo is None or memo[0] != acc.epoch:
                memo = (acc.epoch, acc.resolve(
                    getattr(prepared, "access_profile", ())))
                prepared._access_memo = memo
            acc.fold_resolved(memo[1])
        return rs

    def _result_cache_put(self, rc_key, hit: "_FastHit", rs) -> None:
        """Admit a freshly executed fused result: only clean narrowed
        frames small enough for the entry cap — the cursor reference
        pins the device-resident frame (that is the 'device cache' half;
        the decoded host columns make hits free of fold work too)."""
        rc = self.result_cache
        cur = getattr(rs, "_cursor", None)
        if rc is None or cur is None:
            return
        if not getattr(cur, "narrowed", False) \
                or getattr(cur, "_fallback", False):
            return
        nbytes = sum(
            int(getattr(a, "nbytes", 0))
            for d in (cur._hcols, cur._hvalid) for a in d.values()
        ) + int(getattr(cur._hsel, "nbytes", 0))
        if nbytes > rc.entry_limit:
            return
        try:
            cols = rs.columns
        except Exception:
            return
        rc.put(rc_key, rs.names, {n: cols[n] for n in rs.names}, nbytes,
               getattr(hit.fe, "tables", ()), cursor=cur)

    def fast_execute(self, hit: "_FastHit", fastparse_s: float = 0.0,
                     rc_key=_RC_UNSET) -> ResultSet:
        """Execute a fast-tier hit: bind + dispatch the cached executable.
        Any failure drops the text entry (the next occurrence re-registers
        through the full path) and re-raises for the retry controller.
        `rc_key` carries a result-cache identity the caller already
        probed (the server fast path probes before the batcher bracket);
        left unset, this probes/admits the cache itself."""
        profiling = (self.profile_enabled_fn() if self.profile_enabled_fn
                     else True)
        if rc_key is _RC_UNSET:
            rc_key = self.result_cache_key(hit)
            rs = self.result_cache_probe(hit, rc_key, fastparse_s)
            if rs is not None:
                return rs
        h2d0 = self.executor.h2d_bytes if profiling else 0
        try:
            rs = self._execute_entry(
                hit.entry, hit.values, ex=self.executor, was_hit=True,
                fast=True, plan_s=0.0, compile_s=0.0,
                fastparse_s=fastparse_s, profiling=profiling, h2d0=h2d0,
                plan_obj=getattr(hit.entry.prepared, "plan", None),
            )
        except Exception:
            self.plan_cache.fast_invalidate(hit.text_key)
            raise
        if rc_key is not None:
            try:
                self._result_cache_put(rc_key, hit, rs)
            except Exception:
                pass  # cache admission must never fail the statement
        return rs

    def cached_entry(self, text: str):
        """(CacheEntry, bound qparams) for a statement already run through
        sql() — the compiled-executable surface consumers (bench timing
        loops) use to re-run the exact cached artifact without a second
        trace/compile. Returns (None, None) on a cache miss."""
        norm_key, _ = P.normalize_for_cache(text)
        planned = self.planner.plan(P.parse(text))
        pz = parameterize(planned.plan)
        key = self._cache_key(norm_key, pz)
        entry = self.plan_cache.get(key)
        if entry is None:
            return None, None
        if hasattr(entry.prepared, "bind"):
            # the SAME dispatch form sql() used (packed int64 vector):
            # a tuple here would change the jit signature and silently
            # re-trace + re-compile the plan (review finding)
            return entry, entry.prepared.bind(pz.values, entry.dtypes)
        return entry, bind(pz.values, entry.dtypes)

    def _cache_key(self, norm_key: str, pz, executor=None) -> tuple:
        return self._key_parts(norm_key, pz, executor)[0]

    def _key_parts(self, norm_key: str, pz, executor=None
                   ) -> tuple[tuple, tuple, str]:
        """(logical cache key, referenced table names, plan fingerprint).
        The tables and fingerprint also seed fast-tier registration — a
        fast hit rebuilds this key from them without planning."""
        tables = tuple(sorted(
            {s.table for s in self.executor._collect_scans(pz.plan)}
        ))
        extra = self.key_extra_fn(tables) if self.key_extra_fn is not None \
            else ()
        # an executor override (PX routing) compiles a DIFFERENT program
        # for the same text: the entry must not collide with single-chip
        if executor is not None and executor is not self.executor:
            extra = (*extra, "#exec", id(executor))
        fp = plan_fingerprint(pz.plan)
        # id(catalog) scopes entries to one table set (cache sharing is per
        # tenant = per catalog; entries pin their executor -> catalog, so the
        # id cannot be recycled while the entry lives); the plan fingerprint
        # catches literals consumed at plan time (ORDER BY ordinals etc.)
        key = (id(self.catalog), norm_key, pz.sig, pz.baked, fp, extra)
        return key, tables, fp

    def _artifact_key(self, norm_key: str, pz, fp: str, tables,
                      executor=None) -> tuple | None:
        """Restart-stable identity of a compiled artifact: the logical
        cache key minus process-local ids — id(catalog) drops (the store
        is scoped per database), and a PX override contributes its shard
        count instead of its executor's object id. The schema/dict
        versions in `extra` still invalidate exactly like the in-memory
        key."""
        extra = self.key_extra_fn(tables) if self.key_extra_fn is not None \
            else ()
        tag: tuple = ()
        if executor is not None and executor is not self.executor:
            nsh = getattr(executor, "nsh", 0)
            if not nsh:
                return None  # unknown override: don't risk a collision
            # full mesh signature, not just the device count: an SPMD
            # program's shardings are lowered against axis sizes + names,
            # and 8x1 vs 4x2 (or renamed axes) must never share artifacts
            sig = getattr(executor, "mesh_sig", ()) or ()
            tag = ("#px", int(nsh), *sig)
        return (norm_key, pz.sig, pz.baked, fp, extra, tag)

    def _emit_px_spans(self, prepared, start: float, end: float) -> None:
        """Per-DFO / per-shard worker spans for a PX execution, stitched
        under the active statement span. Works for CACHED plans too: the
        exchange layout rides the prepared plan from compile time."""
        tr = self.tracer
        exchanges = getattr(prepared, "px_exchanges", None)
        if tr is None or not tr.enabled or exchanges is None:
            return
        ctx = tr.current_ctx()
        nsh = getattr(prepared, "px_nsh", 1)
        coord = tr.record_span("px coordinator", ctx, start, end, dop=nsh)
        cctx = (coord.trace_id, coord.span_id) if coord is not None else ctx
        if exchanges:
            for i, (kind, ncols, cap) in enumerate(exchanges):
                for node in range(nsh):
                    tr.record_span(
                        "px worker", cctx, start, end, node=node, dfo=i,
                        exchange=kind, lane_cap=cap, cols=ncols,
                    )
        else:
            # exchange-free plan (fully local per shard): one worker span
            # per mesh device so the trace still shows the fan-out
            for node in range(nsh):
                tr.record_span("px worker", cctx, start, end, node=node,
                               dfo=0)

    def run_ast(self, ast, norm_key: str, use_cache: bool | None = None,
                executor=None, fast_reg=None,
                fastparse_s: float = 0.0) -> ResultSet:
        """Plan + execute an already-parsed SELECT under the plan cache.

        Shared by text queries and internal consumers (the DML layer's
        UPDATE/DELETE qualification scans, virtual-table queries).
        use_cache=False bypasses the plan cache entirely (virtual-table
        statements: their per-materialization dictionaries make entries
        never reusable, and caching them would evict user plans).
        `executor` overrides the compiling/executing backend for this
        statement (PX routing: the server layer passes its PxExecutor when
        the session's DOP variable asks for distributed execution).
        `fast_reg` = (text_key, raw_params, kinds) from fast_normalize
        registers this statement in the text-keyed fast tier on success —
        callers pass it only for plain cacheable single-chip statements."""
        if getattr(ast, "ctes", None):
            from .recursive import recursive_cte_of, run_recursive

            if recursive_cte_of(ast) is not None:
                out_batch, names = run_recursive(self, ast)
                host = batch_to_host(out_batch)
                return ResultSet(tuple(names), {n: host[n] for n in names})
        # JSON_OBJECT/JSON_ARRAY select items: device executes the argument
        # columns, host formats the JSON text at result assembly
        # (sql/json_host.py); the spec joins the cache key — same
        # normalized text with different constructor literals must not
        # share an entry
        from ..sql.json_host import split_host_json

        try:
            ast, jspecs, jhidden = split_host_json(ast)
        except ValueError as err:
            from ..sql.logical import ResolveError

            raise ResolveError(str(err)) from None
        if jspecs:
            norm_key = f"{norm_key}|jh:{jspecs!r}"
        ex = executor if executor is not None else self.executor
        t0 = time.perf_counter()
        planned = self.planner.plan(ast)
        pz = parameterize(planned.plan)
        key, tables, fp = self._key_parts(norm_key, pz, executor)
        plan_s = time.perf_counter() - t0
        if use_cache is None:
            use_cache = self.cache_enabled_fn() if self.cache_enabled_fn else True
        entry = self.plan_cache.get(key) if use_cache else None
        was_hit = entry is not None
        profiling = (self.profile_enabled_fn() if self.profile_enabled_fn
                     else True)
        h2d0 = ex.h2d_bytes if profiling else 0
        compile_s = 0.0
        # on-disk artifact tier: a logical miss tries to hydrate the
        # exported executable before paying a compile. JSON-split
        # statements stay memory-only (their host formatting spec rides
        # the entry, not the executable).
        art_store = getattr(self.plan_cache, "artifact_store", None)
        art_key = None
        if art_store is not None and use_cache and not jspecs:
            art_key = self._artifact_key(norm_key, pz, fp, tables, executor)
        hydrated = False
        if entry is None and art_key is not None and art_store.readable:
            t0 = time.perf_counter()
            got = art_store.hydrate(art_store.key_id(art_key), ex)
            if got is not None:
                _meta, prepared = got
                compile_s = time.perf_counter() - t0
                entry = CacheEntry(prepared, planned.output_names, pz.dtypes)
                entry.json_specs, entry.json_hidden = jspecs, jhidden
                if self.plan_monitor is not None and self.plan_monitor.enabled:
                    entry.monitor = self.plan_monitor.register(
                        norm_key, compile_s)
                self.plan_cache.put(key, entry)
                hydrated = True
        if entry is None:
            t0 = time.perf_counter()
            prepared = ex.prepare(pz.plan)
            compile_s = time.perf_counter() - t0
            entry = CacheEntry(prepared, planned.output_names, pz.dtypes)
            entry.json_specs, entry.json_hidden = jspecs, jhidden
            if self.plan_monitor is not None and self.plan_monitor.enabled:
                entry.monitor = self.plan_monitor.register(norm_key, compile_s)
            if use_cache:
                self.plan_cache.put(key, entry)
        rs = self._execute_entry(
            entry, pz.values, ex=ex, was_hit=was_hit, fast=False,
            plan_s=plan_s, compile_s=compile_s, fastparse_s=fastparse_s,
            profiling=profiling, h2d0=h2d0, plan_obj=pz.plan,
        )
        # text-tier registration AFTER a successful execution: one entry
        # per kind-marked normalized text, carrying the logical key parts
        # + token->slot accounting. PX overrides, JSON-split statements
        # and cache-bypassed (virtual-table) statements never register.
        if fast_reg is not None and use_cache and executor is None \
                and not jspecs:
            fkey, params, kinds = fast_reg
            self.plan_cache.fast_put(fkey, FastEntry(
                norm_key=norm_key, sig=pz.sig, baked=pz.baked,
                fingerprint=fp, tables=tables,
                slot_map=build_slot_map(params, kinds, pz.values),
                base_values=tuple(pz.values),
                stmt_type=type(ast).__name__,
            ))
        # artifact export AFTER a successful execution of a FRESH compile
        # (a hit/hydrate already has its executable on disk). The fast-
        # tier registration material rides the artifact so a warm boot
        # restores the text tier too.
        if art_key is not None and not was_hit and not hydrated \
                and art_store.writable:
            art_fast = art_text = None
            if fast_reg is not None and executor is None:
                fkey, params, kinds = fast_reg
                art_text = fkey
                art_fast = dict(
                    norm_key=norm_key, sig=pz.sig, baked=pz.baked,
                    fingerprint=fp, tables=tables,
                    slot_map=build_slot_map(params, kinds, pz.values),
                    base_values=tuple(pz.values),
                    stmt_type=type(ast).__name__,
                )
            try:
                art_store.save(
                    art_key, entry.prepared,
                    output_names=planned.output_names, dtypes=pz.dtypes,
                    tables=tables, fast=art_fast, text_key=art_text)
            except Exception:
                pass
        return rs

    def _execute_entry(self, entry, values, *, ex, was_hit, fast, plan_s,
                       compile_s, fastparse_s, profiling, h2d0,
                       plan_obj) -> ResultSet:
        """Bind + dispatch a cached/compiled entry and assemble the
        ResultSet, profile, monitor row, phase breakdown and metrics.
        Shared by the full path (run_ast) and the fast path
        (fast_execute) — the fast path arrives with plan_s=compile_s=0.

        Single-chip plans take the LAZY route: dispatch is async
        (PreparedPlan.run_device returns device references immediately),
        sql_audit/metrics/trace host work overlaps device compute, and the
        only in-statement sync is the overflow-counter + row-count fetch.
        Column data stays device-resident behind the DeviceResult cursor
        until the caller touches it."""
        from ..share.errsim import errsim_point
        from ..sql.json_host import apply_host_json

        if not getattr(ex, "host_fallback", False):
            # device OOM injection point (EN_DEVICE_OOM): covers the fast
            # path, the full path and chunked dispatch alike. A host-
            # fallback executor never device-OOMs, which is what lets the
            # degradation ladder's final rung terminate.
            errsim_point("EN_DEVICE_OOM")
        jn = getattr(entry, "json_specs", ())
        prepared = entry.prepared
        retries0 = getattr(prepared, "retries", 0)
        ann0 = getattr(
            getattr(prepared, "params", None), "ann_escalations", 0)
        # streaming pipeline counters are cumulative on the prepared plan
        # (plan-cache shared): fold per-run deltas, like overflow retries
        sstats = getattr(prepared, "stream_stats", None)
        stream0 = sstats.snapshot() if sstats is not None else None
        t0 = time.perf_counter()
        if hasattr(prepared, "run_host"):
            # packed parameter upload: ONE host->device transfer for the
            # whole parameter set
            qparams = prepared.bind(values, entry.dtypes)
        else:
            # chunked / PX prepared plans: legacy tuple contract
            qparams = bind(values, entry.dtypes)
        bind_s = time.perf_counter() - t0
        d2h_bytes = 0
        fetch_s = 0.0
        exec_t0 = time.perf_counter()
        lazy = hasattr(prepared, "run_device") and not jn
        self.last_op_profile = None
        op_samples = prof_digest = prof_reason = None
        narrow = None  # (novf, ncap) when the dispatch was fused-narrowed
        if lazy:
            from .executor import DeviceResult, NarrowDeviceResult

            pp = self.plan_profiler
            if pp is not None and pp.enabled:
                from . import plan_profile as _PP

                if _PP.profile_eligible(prepared):
                    # the server layer hands the statement digest down
                    # thread-locally; direct engine use falls back to the
                    # monitor's normalized text as the sampling key
                    mon0 = getattr(entry, "monitor", None)
                    prof_digest = pp.take_pending() or (
                        mon0.sql if mon0 is not None else None)
                    if prof_digest is not None:
                        prof_reason = pp.decide(prof_digest)
            out = None
            if prof_reason is not None:
                from . import plan_profile as _PP

                try:
                    # profiled segmented run: fenced per-operator stages,
                    # bit-identical (out, ovf_vec) — the statement is
                    # served FROM this run, nothing executes twice
                    out, ovf_vec, op_samples = _PP.run_profiled(
                        prepared, qparams)
                except Exception:
                    # a broken profile never fails the statement — fall
                    # back to the fused dispatch below
                    out = None
            if out is None:
                # whole-statement fusion: compile the final result-frame
                # gather INTO the plan's device program — one dispatch,
                # and the completion sync moves only the frame's bytes
                nfn = self.narrow_enabled_fn
                # AOT-hydrated plans stay un-narrowed until a natural
                # recompile makes them traceable again: building the
                # narrow program would force the honest recompile that
                # the zero-compile warm-boot promise forbids
                if ((nfn is None or nfn()) and ex is self.executor
                        and getattr(prepared, "_traceable", True)
                        and hasattr(prepared, "narrow_frame")):
                    ncap = prepared.narrow_frame(
                        self.narrow_default_rows, self.narrow_max_rows)
                    if ncap:
                        out, ovf_vec, novf = prepared.run_device_narrow(
                            qparams, ncap)
                        narrow = (novf, ncap)
            if out is None:
                out, ovf_vec = prepared.run_device(qparams=qparams)
            dispatch_s = time.perf_counter() - exec_t0
            if narrow is not None:
                cursor = NarrowDeviceResult(
                    prepared, qparams, out, ovf_vec, narrow[0], narrow[1],
                    self.narrow_max_rows)
            else:
                cursor = DeviceResult(prepared, qparams, out, ovf_vec)
            rs = LazyResultSet(entry.output_names, cursor,
                               plan_cache_hit=was_hit, fast_path_hit=fast)
        elif hasattr(prepared, "run_host"):
            # eager single-device_get dispatch (kept for JSON-split
            # statements whose host formatting needs every column anyway)
            from ..core.column import host_rows

            hcols, hvalid, hsel, oschema, odicts = prepared.run_host(
                qparams=qparams)
            dispatch_s = time.perf_counter() - exec_t0
            if profiling:
                d2h_bytes = sum(
                    int(getattr(a, "nbytes", 0))
                    for d in (hcols, hvalid)
                    for a in d.values()
                ) + int(getattr(hsel, "nbytes", 0))
            host = host_rows(oschema, odicts, hcols, hvalid, hsel)
            rs = None
        else:
            # chunked / PX prepared plans: device-batch contract
            out_batch = prepared.run(qparams=qparams)
            dispatch_s = time.perf_counter() - exec_t0
            host = batch_to_host(out_batch)
            if profiling:
                d2h_bytes = sum(
                    int(getattr(a, "nbytes", 0)) for a in host.values()
                )
            rs = None
        self._emit_px_spans(prepared, exec_t0, time.perf_counter())
        if rs is None:
            # order columns per select list
            cols = {n: host[n] for n in entry.output_names}
            out_names = entry.output_names
            if jn:
                out_names, cols = apply_host_json(
                    jn, entry.json_hidden, out_names, cols)
            rs = ResultSet(out_names, cols, plan_cache_hit=was_hit,
                           fast_path_hit=fast)
        profile = None
        if profiling:
            from ..server.diag import QueryProfile

            device_bytes = 0
            input_spec = getattr(prepared, "input_spec", None)
            if input_spec is not None:
                # warm statements reuse the footprint walk: device inputs
                # only change via an upload, and every upload moves the
                # executor's lifetime h2d counter (serving-path diet)
                memo = getattr(prepared, "_dev_bytes_memo", None)
                if (memo is not None and memo[0] == ex.h2d_bytes
                        and memo[1] is ex):
                    device_bytes = memo[2]
                else:
                    device_bytes = ex.input_device_bytes(input_spec)
                    prepared._dev_bytes_memo = (
                        ex.h2d_bytes, ex, device_bytes)
            if lazy:
                # result footprint measured on-device (no transfer): the
                # cursor adds actual d2h bytes as fetches happen. Output
                # shapes are static per compiled executable, so warm
                # statements reuse the walk (invalidated by a recompile)
                rmemo = getattr(prepared, "_result_bytes_memo", None)
                if narrow is not None:
                    # narrowed frame bytes — NOT memoized: the memo feeds
                    # the base cursor's small-result heuristic against
                    # the UN-narrowed output shape
                    result_bytes = sum(
                        int(getattr(a, "nbytes", 0))
                        for d in (out.cols, out.valid) for a in d.values()
                    ) + int(getattr(out.sel, "nbytes", 0))
                elif rmemo is not None and rmemo[0] == retries0:
                    result_bytes = rmemo[1]
                else:
                    result_bytes = sum(
                        int(getattr(a, "nbytes", 0))
                        for d in (out.cols, out.valid) for a in d.values()
                    ) + int(getattr(out.sel, "nbytes", 0))
                    prepared._result_bytes_memo = (retries0, result_bytes)
            else:
                result_bytes = d2h_bytes
            # peak working set: device-resident inputs + the result's
            # footprint + PX exchange lane capacity (the collective's
            # buffers are live simultaneously with both)
            peak = device_bytes + result_bytes
            for _kind, ncols, cap in getattr(prepared, "px_exchanges", ()):
                nsh = getattr(prepared, "px_nsh", 1)
                lanes = nsh if _kind == "broadcast" else nsh * nsh
                peak += ncols * cap * lanes * 8
            profile = QueryProfile(
                compile_hit=was_hit,
                compile_s=compile_s,
                h2d_bytes=ex.h2d_bytes - h2d0,
                d2h_bytes=d2h_bytes,
                device_bytes=device_bytes,
                peak_bytes=peak,
                fastparse_s=fastparse_s,
                bind_s=bind_s,
                dispatch_s=dispatch_s,
                fetch_s=fetch_s,
                fast_path_hit=fast,
            )
        self.last_profile = profile
        self.last_plan = plan_obj
        phases = {
            "plan_s": plan_s, "compile_s": compile_s,
            "fastparse_s": fastparse_s, "bind_s": bind_s,
            "dispatch_s": dispatch_s, "fetch_s": fetch_s,
            "cache_hit": was_hit, "fast_hit": fast,
        }
        self.last_phases = phases
        if lazy:
            # wire the in-place observability sinks, THEN force the sync
            # point: the overflow check + row count (two scalars). All the
            # host work above overlapped device compute. The sync wall IS
            # the statement's device wait — time it (host-tax ledger's
            # "device wait" phase reads fetch_s; leaving it 0.0 hid the
            # chip time inside exec_s).
            cursor.profile = profile
            cursor.phases = phases
            tf = time.perf_counter()
            nrows = rs.nrows
            fetch_s = time.perf_counter() - tf
            phases["fetch_s"] = fetch_s
            if profile is not None:
                profile.fetch_s = fetch_s
        else:
            nrows = rs.nrows
        exec_s = time.perf_counter() - exec_t0
        phases["exec_s"] = exec_s
        phases["rows"] = nrows
        acc = self.access
        if acc is not None and acc.enabled:
            # access heat: the profile resolves to live stat objects once
            # per (prepared, epoch); every execution after that folds
            # through direct references (no dict lookups)
            memo = getattr(prepared, "_access_memo", None)
            if memo is None or memo[0] != acc.epoch:
                memo = (acc.epoch, acc.resolve(
                    getattr(prepared, "access_profile", ())))
                prepared._access_memo = memo
            if memo[1]:
                acc.fold_resolved(memo[1])
        # mesh-SPMD collective accounting: the MeshPlan rides the prepared
        # plan (filled at first-dispatch trace, restored warm from the
        # artifact store), so cached and warm-booted plans fold identically
        mesh_plan = getattr(prepared, "mesh_plan", None)
        if mesh_plan is not None and not mesh_plan.total_ops:
            mesh_plan = None
        stream_d = None
        if sstats is not None:
            s1 = sstats.snapshot()
            d = tuple(b - a for a, b in zip(stream0, s1))
            if d[0] or d[6]:  # chunks streamed or partitions spilled
                stream_d = d
                # streamed plans execute inside dispatch_s; expose the
                # per-chunk H2D/compute/overlap split so the host-tax
                # ledger can carve the dispatch wall into real phases
                phases["stream_h2d_s"] = d[3]
                phases["stream_compute_s"] = d[4]
                phases["stream_overlap_s"] = d[5]
        mon = getattr(entry, "monitor", None)
        if mon is not None:
            mon.runs += 1
            mon.total_exec_s += exec_s
            mon.last_rows = nrows
            mon.overflow_retries = getattr(prepared, "retries", 0)
            if profile is not None:
                mon.total_transfer_bytes += profile.transfer_bytes
                mon.last_device_bytes = profile.device_bytes
                mon.peak_bytes = max(mon.peak_bytes, profile.peak_bytes)
            if mesh_plan is not None:
                mon.px_collective_ops += mesh_plan.total_ops
                mon.px_collective_bytes += mesh_plan.total_bytes
                mon.px_exchanges = mesh_plan.describe()
            if stream_d is not None:
                mon.stream_chunks += stream_d[0]
                mon.spill_partitions += stream_d[6]
                h2d_d, overlap_d = stream_d[3], stream_d[5]
                mon.h2d_overlap_pct = (
                    100.0 * overlap_d / h2d_d if h2d_d else 0.0)
        if op_samples is not None and self.plan_profiler is not None:
            # fold the (estimate, actual) calibration pairs into the
            # bounded store + per-op-kind sysstat counters; EXPLAIN
            # ANALYZE reads last_op_profile right after this run
            est = getattr(prepared, "node_estimates", None)
            self.plan_profiler.store.fold(
                prof_digest, op_samples, est,
                plan_id=mon.plan_id if mon is not None else 0,
            )
            seg = getattr(prepared, "_segmented", None)
            self.last_op_profile = {
                "digest": prof_digest,
                "reason": prof_reason,
                "estimates": dict(est or {}),
                "samples": op_samples,
                # plan nodes the executor never emits standalone (e.g.
                # a Join absorbed by a clustered-FK aggregate): no
                # sample, charged to the absorbing parent
                "absorbed": dict(getattr(seg, "absorbed", None) or {}),
            }
            pm = self.metrics
            if pm is not None and pm.enabled:
                pm.add("plan profiles")
                pm.add(f"plan profiles: {prof_reason}")
                for s in op_samples:
                    pm.add(f"plan profile ops: {s.op_kind}")
        m = self.metrics
        if m is not None and m.enabled:
            m.observe("sql plan", plan_s)
            if not was_hit:
                m.observe("sql compile", compile_s)
            m.observe("sql execute", exec_s)
            m.add("result rows returned", nrows)
            if narrow is not None:
                m.add("stmt fused dispatches")
            retries = getattr(prepared, "retries", 0) - retries0
            if retries > 0:
                m.add("overflow recompiles", retries)
            params = getattr(prepared, "params", None)
            vts = getattr(params, "vector_topns", None)
            if vts:
                m.add("ann probes",
                      sum(v.nprobe for v in vts.values()))
                esc = getattr(params, "ann_escalations", 0) - ann0
                if esc > 0:
                    m.add("ann over-probe escalations", esc)
                stats = getattr(ex, "ann_stats", None)
                if stats is not None:
                    for v in vts.values():
                        st = stats.setdefault(
                            (v.table, v.column), [0, 0, 0])
                        st[0] += 1
                        st[1] += v.nprobe
                        st[2] += max(esc, 0)
            if mesh_plan is not None:
                for coll, cnt in mesh_plan.ops_by_collective().items():
                    m.add(f"px collective {coll}", cnt)
                m.add("px collective bytes", mesh_plan.total_bytes)
            if stream_d is not None:
                m.add("stream chunks", stream_d[0])
                m.add("stream h2d overlap", int(stream_d[5] * 1e6))
                if stream_d[6]:
                    m.add("stream spill partitions", stream_d[6])
        tl = self.timeline
        if tl is not None and tl.enabled:
            # serving timeline: this dispatch's device-busy seconds plus
            # compile/result-transfer interference. Batched cohorts skip
            # this path — their ONE shared dispatch is fed by the batcher
            tl.record_exec(dispatch_s, 0.0 if was_hit else compile_s,
                           d2h_bytes)
            if mesh_plan is not None:
                tl.record_collective(
                    mesh_plan.total_ops, mesh_plan.total_bytes)
            if stream_d is not None:
                tl.record_stream(stream_d[0], stream_d[3], stream_d[4],
                                 stream_d[5], stream_d[6])
        return rs
