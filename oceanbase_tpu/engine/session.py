"""Session facade: SQL text in, result rows out, with a plan cache.

Reference surface: ObSql::stmt_query + ObPlanCache
(src/sql/ob_sql.cpp:153, src/sql/plan_cache/ob_plan_cache.h:227). The cache
key is the literal-normalized SQL text (fast-parser analog,
sql/parser.py normalize_for_cache); a hit reuses the compiled jitted
program — the expensive artifact on TPU is the XLA executable, so the plan
cache IS the compile cache.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..core.column import batch_to_host
from ..core.table import Table
from ..sql import parser as P
from ..sql.plan_cache import (
    CacheEntry,
    PlanCache,
    bind,
    parameterize,
    plan_fingerprint,
)
from ..sql.planner import Planner
from .executor import Executor


@dataclass
class ResultSet:
    names: tuple[str, ...]
    columns: dict[str, object]  # name -> np.ndarray | list
    affected: int = 0  # DML-affected row count (0 for queries)
    plan_cache_hit: bool = False  # this statement reused a compiled plan

    @property
    def nrows(self) -> int:
        if not self.names:
            return 0
        c = self.columns[self.names[0]]
        return len(c)

    def rows(self) -> list[tuple]:
        cols = [self.columns[n] for n in self.names]
        return list(zip(*cols)) if cols else []


class Session:
    def __init__(self, catalog: dict[str, Table], unique_keys=None,
                 plan_cache: PlanCache | None = None, key_extra_fn=None,
                 cache_enabled_fn=None, plan_monitor=None, views=None,
                 metrics=None, tracer=None, profile_enabled_fn=None):
        self.catalog = catalog
        from ..share.stats import StatsManager

        self.stats = StatsManager(catalog)
        self.planner = Planner(
            catalog, stats=self.stats, unique_keys=unique_keys, views=views
        )
        self.executor = Executor(
            catalog, unique_keys=unique_keys, stats=self.stats
        )
        # shareable across sessions (the reference's cache is per-tenant,
        # not per-session: ob_plan_cache.h:227)
        self.plan_cache = plan_cache if plan_cache is not None else PlanCache()
        # hook: extra cache-key material per referenced table set (the
        # DML-backed catalog keys entries on table dict versions, since
        # string literals bake dictionary lookups at trace time)
        self.key_extra_fn = key_extra_fn
        # hook: ob_enable_plan_cache (a disabled cache compiles every time)
        self.cache_enabled_fn = cache_enabled_fn
        # hook: server/diag.PlanMonitor (per-plan compile/exec stats)
        self.plan_monitor = plan_monitor
        # hook: share/metrics.MetricsRegistry (phase histograms + counters)
        self.metrics = metrics
        # hook: server/diag.Tracer — PX executions stitch per-DFO worker
        # spans into the active statement's trace through it
        self.tracer = tracer
        # hook: config enable_query_profile (None = always profile)
        self.profile_enabled_fn = profile_enabled_fn
        # per-statement phase breakdown of the LAST run_ast call (EXPLAIN
        # ANALYZE reads it right after executing the analyzed statement)
        self.last_phases: dict = {}
        # per-statement TPU resource attribution (server/diag.QueryProfile)
        # of the LAST run_ast call; None when profiling is off or the
        # statement bypassed run_ast (pure DDL)
        self.last_profile = None
        # logical plan of the LAST run_ast call (flight-recorder bundles
        # capture its repr as the plan text)
        self.last_plan = None

    def materialize(self, text: str, name: str) -> Table:
        """Run a SELECT and materialize its result as a storage-domain
        Table (exact round-trip: decimals stay scaled ints, dates stay
        day numbers, NULLs keep their validity masks) — the engine half
        of materialized views."""
        from ..core.column import (
            batch_rows_storage,
            batch_valid_storage,
            renamed_storage_schema,
        )
        from ..sql.logical import output_schema
        from .recursive import recursive_cte_of, run_recursive

        ast = P.parse(text)
        if getattr(ast, "ctes", None) and recursive_cte_of(ast) is not None:
            batch, out_names = run_recursive(self, ast)
            names = list(out_names)
            schema_src = batch.schema
        else:
            planned = self.planner.plan(ast)
            schema_src = output_schema(planned.plan)
            batch = self.executor.execute(planned.plan)
            names = list(planned.output_names)
        valid = batch_valid_storage(batch, names)
        schema = renamed_storage_schema(schema_src, names)
        if valid:
            # a validity mask forces the field nullable, or make_batch
            # would drop the mask on the next read
            from dataclasses import replace as _rp

            from ..core.dtypes import Field as _F, Schema as _S

            schema = _S(tuple(
                _F(f.name, _rp(f.dtype, nullable=True))
                if f.name in valid else f
                for f in schema.fields
            ))
        return Table(
            name,
            schema,
            batch_rows_storage(batch, names),
            {n: batch.dicts[n] for n in names if n in batch.dicts},
            valid,
        )

    def sql(self, text: str) -> ResultSet:
        norm_key, _ = P.normalize_for_cache(text)
        # parse + logical plan always run (host-cheap, the fast-parser
        # analog); the cache skips trace + XLA compile (the expensive part)
        ast = P.parse(text)
        return self.run_ast(ast, norm_key)

    def cached_entry(self, text: str):
        """(CacheEntry, bound qparams) for a statement already run through
        sql() — the compiled-executable surface consumers (bench timing
        loops) use to re-run the exact cached artifact without a second
        trace/compile. Returns (None, None) on a cache miss."""
        norm_key, _ = P.normalize_for_cache(text)
        planned = self.planner.plan(P.parse(text))
        pz = parameterize(planned.plan)
        key = self._cache_key(norm_key, pz)
        entry = self.plan_cache.get(key)
        if entry is None:
            return None, None
        if hasattr(entry.prepared, "bind"):
            # the SAME dispatch form sql() used (packed int64 vector):
            # a tuple here would change the jit signature and silently
            # re-trace + re-compile the plan (review finding)
            return entry, entry.prepared.bind(pz.values, entry.dtypes)
        return entry, bind(pz.values, entry.dtypes)

    def _cache_key(self, norm_key: str, pz, executor=None) -> tuple:
        extra = ()
        if self.key_extra_fn is not None:
            tables = tuple(sorted(
                {s.table for s in self.executor._collect_scans(pz.plan)}
            ))
            extra = self.key_extra_fn(tables)
        # an executor override (PX routing) compiles a DIFFERENT program
        # for the same text: the entry must not collide with single-chip
        if executor is not None and executor is not self.executor:
            extra = (*extra, "#exec", id(executor))
        # id(catalog) scopes entries to one table set (cache sharing is per
        # tenant = per catalog; entries pin their executor -> catalog, so the
        # id cannot be recycled while the entry lives); the plan fingerprint
        # catches literals consumed at plan time (ORDER BY ordinals etc.)
        return (id(self.catalog), norm_key, pz.sig, pz.baked,
                plan_fingerprint(pz.plan), extra)

    def _emit_px_spans(self, prepared, start: float, end: float) -> None:
        """Per-DFO / per-shard worker spans for a PX execution, stitched
        under the active statement span. Works for CACHED plans too: the
        exchange layout rides the prepared plan from compile time."""
        tr = self.tracer
        exchanges = getattr(prepared, "px_exchanges", None)
        if tr is None or not tr.enabled or exchanges is None:
            return
        ctx = tr.current_ctx()
        nsh = getattr(prepared, "px_nsh", 1)
        coord = tr.record_span("px coordinator", ctx, start, end, dop=nsh)
        cctx = (coord.trace_id, coord.span_id) if coord is not None else ctx
        if exchanges:
            for i, (kind, ncols, cap) in enumerate(exchanges):
                for node in range(nsh):
                    tr.record_span(
                        "px worker", cctx, start, end, node=node, dfo=i,
                        exchange=kind, lane_cap=cap, cols=ncols,
                    )
        else:
            # exchange-free plan (fully local per shard): one worker span
            # per mesh device so the trace still shows the fan-out
            for node in range(nsh):
                tr.record_span("px worker", cctx, start, end, node=node,
                               dfo=0)

    def run_ast(self, ast, norm_key: str, use_cache: bool | None = None,
                executor=None) -> ResultSet:
        """Plan + execute an already-parsed SELECT under the plan cache.

        Shared by text queries and internal consumers (the DML layer's
        UPDATE/DELETE qualification scans, virtual-table queries).
        use_cache=False bypasses the plan cache entirely (virtual-table
        statements: their per-materialization dictionaries make entries
        never reusable, and caching them would evict user plans).
        `executor` overrides the compiling/executing backend for this
        statement (PX routing: the server layer passes its PxExecutor when
        the session's DOP variable asks for distributed execution)."""
        if getattr(ast, "ctes", None):
            from .recursive import recursive_cte_of, run_recursive

            if recursive_cte_of(ast) is not None:
                out_batch, names = run_recursive(self, ast)
                host = batch_to_host(out_batch)
                return ResultSet(tuple(names), {n: host[n] for n in names})
        # JSON_OBJECT/JSON_ARRAY select items: device executes the argument
        # columns, host formats the JSON text at result assembly
        # (sql/json_host.py); the spec joins the cache key — same
        # normalized text with different constructor literals must not
        # share an entry
        from ..sql.json_host import apply_host_json, split_host_json

        try:
            ast, jspecs, jhidden = split_host_json(ast)
        except ValueError as err:
            from ..sql.logical import ResolveError

            raise ResolveError(str(err)) from None
        if jspecs:
            norm_key = f"{norm_key}|jh:{jspecs!r}"
        ex = executor if executor is not None else self.executor
        t0 = time.perf_counter()
        planned = self.planner.plan(ast)
        pz = parameterize(planned.plan)
        key = self._cache_key(norm_key, pz, executor)
        plan_s = time.perf_counter() - t0
        if use_cache is None:
            use_cache = self.cache_enabled_fn() if self.cache_enabled_fn else True
        entry = self.plan_cache.get(key) if use_cache else None
        was_hit = entry is not None
        profiling = (self.profile_enabled_fn() if self.profile_enabled_fn
                     else True)
        h2d0 = ex.h2d_bytes if profiling else 0
        compile_s = 0.0
        if entry is None:
            t0 = time.perf_counter()
            prepared = ex.prepare(pz.plan)
            compile_s = time.perf_counter() - t0
            entry = CacheEntry(prepared, planned.output_names, pz.dtypes)
            entry.json_specs, entry.json_hidden = jspecs, jhidden
            if self.plan_monitor is not None and self.plan_monitor.enabled:
                entry.monitor = self.plan_monitor.register(norm_key, compile_s)
            if use_cache:
                self.plan_cache.put(key, entry)
        retries0 = getattr(entry.prepared, "retries", 0)
        d2h_bytes = 0
        exec_t0 = time.perf_counter()
        if hasattr(entry.prepared, "run_host"):
            # packed parameter upload + single-device_get dispatch: ONE
            # host->device transfer for the whole parameter set, ONE
            # device->host fetch for results + validity + sel + overflow
            # counters (per-array fetches each cost a tunnel roundtrip)
            from ..core.column import host_rows

            qparams = entry.prepared.bind(pz.values, entry.dtypes)
            t0 = time.perf_counter()
            hcols, hvalid, hsel, oschema, odicts = entry.prepared.run_host(
                qparams=qparams)
            exec_s = time.perf_counter() - t0
            if profiling:
                d2h_bytes = sum(
                    int(getattr(a, "nbytes", 0))
                    for d in (hcols, hvalid)
                    for a in d.values()
                ) + int(getattr(hsel, "nbytes", 0))
            host = host_rows(oschema, odicts, hcols, hvalid, hsel)
        else:
            # chunked / PX prepared plans: device-batch contract
            qparams = bind(pz.values, entry.dtypes)
            t0 = time.perf_counter()
            out_batch = entry.prepared.run(qparams=qparams)
            exec_s = time.perf_counter() - t0
            host = batch_to_host(out_batch)
            if profiling:
                d2h_bytes = sum(
                    int(getattr(a, "nbytes", 0)) for a in host.values()
                )
        self._emit_px_spans(entry.prepared, exec_t0, time.perf_counter())
        # order columns per select list
        cols = {n: host[n] for n in entry.output_names}
        out_names = entry.output_names
        jn = getattr(entry, "json_specs", ())
        if jn:
            out_names, cols = apply_host_json(
                jn, entry.json_hidden, out_names, cols)
        rs = ResultSet(out_names, cols, plan_cache_hit=was_hit)
        profile = None
        if profiling:
            from ..server.diag import QueryProfile

            device_bytes = 0
            input_spec = getattr(entry.prepared, "input_spec", None)
            if input_spec is not None:
                device_bytes = ex.input_device_bytes(input_spec)
            # peak working set: device-resident inputs + the result's
            # footprint + PX exchange lane capacity (the collective's
            # buffers are live simultaneously with both)
            peak = device_bytes + d2h_bytes
            for _kind, ncols, cap in getattr(entry.prepared, "px_exchanges",
                                             ()):
                nsh = getattr(entry.prepared, "px_nsh", 1)
                lanes = nsh if _kind == "broadcast" else nsh * nsh
                peak += ncols * cap * lanes * 8
            profile = QueryProfile(
                compile_hit=was_hit,
                compile_s=compile_s,
                h2d_bytes=ex.h2d_bytes - h2d0,
                d2h_bytes=d2h_bytes,
                device_bytes=device_bytes,
                peak_bytes=peak,
            )
        self.last_profile = profile
        self.last_plan = pz.plan
        mon = getattr(entry, "monitor", None)
        if mon is not None:
            mon.runs += 1
            mon.total_exec_s += exec_s
            mon.last_rows = rs.nrows
            mon.overflow_retries = entry.prepared.retries
            if profile is not None:
                mon.total_transfer_bytes += profile.transfer_bytes
                mon.last_device_bytes = profile.device_bytes
                mon.peak_bytes = max(mon.peak_bytes, profile.peak_bytes)
        self.last_phases = {
            "plan_s": plan_s, "compile_s": compile_s, "exec_s": exec_s,
            "cache_hit": was_hit, "rows": rs.nrows,
        }
        m = self.metrics
        if m is not None and m.enabled:
            m.observe("sql plan", plan_s)
            if not was_hit:
                m.observe("sql compile", compile_s)
            m.observe("sql execute", exec_s)
            m.add("result rows returned", rs.nrows)
            retries = getattr(entry.prepared, "retries", 0) - retries0
            if retries > 0:
                m.add("overflow recompiles", retries)
        return rs
