from .executor import Executor, PhysicalParams
from .session import ResultSet, Session

__all__ = ["Executor", "PhysicalParams", "ResultSet", "Session"]
