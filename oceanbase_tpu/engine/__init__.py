from .executor import Executor, PhysicalParams
from .pipeline import (
    ChunkPrefetcher,
    ChunkStager,
    GraceHashPreparedPlan,
    NotPartitionable,
    StreamStats,
    run_stream,
    try_grace_hash,
)
from .session import ResultSet, Session

__all__ = [
    "ChunkPrefetcher",
    "ChunkStager",
    "Executor",
    "GraceHashPreparedPlan",
    "NotPartitionable",
    "PhysicalParams",
    "ResultSet",
    "Session",
    "StreamStats",
    "run_stream",
    "try_grace_hash",
]
