"""Out-of-core execution: stream chunks of a too-big table through the
jitted plan, merge partial aggregates.

Reference surface: the spill machinery of the vectorized engine — hash
partitioning infrastructure (sql/engine/basic/ob_hp_infras_vec_op.h),
sort/hash-join/hash-agg spill to tmp files (src/storage/tmp_file), and the
SQL memory manager that decides when operators go out-of-core
(ob_tenant_sql_memory_manager.h:580).

TPU redesign: instead of spilling operator state to disk mid-run, the
engine keeps the DEVICE program dense and static — the biggest input table
streams through it in fixed-capacity row chunks (the host arrays are the
"spill tier"), and the plan is algebraically split at its lowest blocking
operator above the streamed scan:

    original:  above_plan( Aggregate_A( stream_path(scan_T, residents...) ) )
    streamed:  for each chunk c of T:   partial_c = Aggregate_A(... chunk ...)
    merged:    above_plan( MergeAggregate( concat(partial_c) ) )

sum/count/min/max partials merge exactly (count merges by sum); avg was
already decomposed into sum/count by the resolver. Joins on the stream path
keep the streamed side as the probe (left) input, so every chunk probes the
same resident build sides — the ObHJPartition analog with the roles fixed
by planning instead of runtime respill.

The chunk capacity is constant across chunks (the last chunk is padded), so
XLA compiles the chunk program exactly once.
"""

from __future__ import annotations

import os
from dataclasses import replace as dc_replace

import numpy as np

from ..core.dtypes import DataType, Field, Schema, TypeKind
from ..core.table import Table
from ..expr import ir as E
from ..sql.logical import (
    Aggregate,
    Distinct,
    Filter,
    JoinOp,
    Limit,
    LogicalOp,
    Project,
    Scan,
    SetOp,
    Sort,
    TopN,
    Window,
    output_schema,
)
from .executor import Executor, _children
from .pipeline import StreamStats, assemble_partials_table, run_stream

import jax
import jax.numpy as jnp


@jax.jit
def _decode_chunk(narrow, bases, count):
    """One-dispatch decode of a narrowed chunk upload: cast each column
    back to its storage width, add its frame-of-reference base, and
    derive the live-row mask. Marker keys '#v:<col>' are validity masks
    (uint8 -> bool)."""
    out = {}
    for k, a in narrow.items():
        if k.startswith("#v:"):
            out[k] = a != 0
        else:
            b = bases[k]
            out[k] = a.astype(b.dtype) + b
    cap = next(iter(narrow.values())).shape[0] if narrow else 0
    sel = jnp.arange(cap, dtype=jnp.int64) < count
    return out, sel


DEFAULT_DEVICE_BUDGET = int(
    os.environ.get("OB_TPU_DEVICE_BUDGET", str(6 << 30))
)
DEFAULT_CHUNK_ROWS = int(os.environ.get("OB_TPU_CHUNK_ROWS", str(1 << 23)))

_MERGE_FN = {"sum": "sum", "count": "sum", "min": "min", "max": "max"}


class NotStreamable(Exception):
    """The plan cannot be split for chunked execution (caller falls back to
    whole-table upload and may simply run out of device memory — the same
    contract as an unspillable operator in the reference)."""


def scan_bytes(catalog, scan: Scan, needed_cols) -> int:
    if scan.table == "$dual":
        return 1
    t = catalog[scan.table]
    cols = needed_cols.get(scan.alias) or set(
        [t.schema.fields[0].name]
    )
    per_row = 0
    for c in cols:
        if c in t.schema:
            per_row += t.schema[c].storage_np.itemsize
    return (t.nrows or 0) * max(per_row, 1)


def plan_input_bytes(executor: Executor, plan: LogicalOp) -> int:
    needed = executor._needed_columns(plan)
    return sum(
        scan_bytes(executor.catalog, s, needed)
        for s in executor._collect_scans(plan)
    )


def _row_bytes(schema: Schema) -> int:
    return max(sum(f.dtype.storage_np.itemsize for f in schema.fields), 1)


def _find_stream_split(executor: Executor, plan: LogicalOp, budget: int):
    """Choose the streamed scan and the chunk-accumulation split node.

    Returns (stream_scan, split_node, kind). `split_node` is the node run
    per chunk; its per-chunk outputs (the "partials") concatenate into the
    $partials relation which the merge plan consumes. Kinds, tried
    most-reducing first along the root->scan path (every node between the
    split and the scan must stream rows: Filter / Project /
    Join-with-stream-on-probe-side):

      agg         lowest Aggregate with mergeable aggs -> re-aggregate
      topn        lowest TopN -> per-chunk top (n+offset), final top-n
      distinct    lowest Distinct -> per-chunk dedup, final dedup
      passthrough the maximal streamable prefix itself (filters, projects,
                  probe joins): partials are the surviving rows; the rest
                  of the plan (sort / window / distinct / set ops / any
                  aggregate) runs unchanged on $partials. Guarded by the
                  optimizer estimate of surviving rows fitting the budget.
    """
    needed = executor._needed_columns(plan)
    scans = executor._collect_scans(plan)
    if not scans:
        raise NotStreamable("no scans")
    sizes = [(scan_bytes(executor.catalog, s, needed), s) for s in scans]
    sizes.sort(key=lambda p: -p[0])
    big, stream = sizes[0]
    rest = sum(b for b, _ in sizes[1:])
    if rest > budget:
        raise NotStreamable("multiple over-budget inputs")
    if sum(1 for s in scans if s.table == stream.table) > 1:
        raise NotStreamable("streamed table scanned more than once")

    # path from root to the streamed scan
    path: list[LogicalOp] = []

    def find(op) -> bool:
        path.append(op)
        if op is stream:
            return True
        for c in _children(op):
            if find(c):
                return True
        path.pop()
        return False

    assert find(plan)

    def path_streams(from_pos: int) -> bool:
        """All nodes strictly below path[from_pos] down to the scan move
        rows chunk-wise."""
        for parent, child in zip(path[from_pos + 1:], path[from_pos + 2:]):
            if isinstance(parent, (Filter, Project)):
                continue
            if isinstance(parent, JoinOp):
                if child is not parent.left:
                    return False
                continue
            if isinstance(parent, Scan):
                continue
            return False
        return True

    # lowest (nearest-scan) candidates per kind
    def lowest(pred):
        best = None
        for i, node in enumerate(path):
            if pred(node):
                best = i
        return best

    i = lowest(lambda n: isinstance(n, Aggregate))
    if i is not None and path_streams(i):
        agg = path[i]
        if all(
            not d and fn in _MERGE_FN for _nm, fn, _a, d in agg.aggs
        ):
            return stream, agg, "agg"

    i = lowest(lambda n: isinstance(n, TopN))
    if i is not None and path_streams(i):
        topn = path[i]
        if all(isinstance(e, E.ColRef) for e, _d in topn.keys):
            return stream, topn, "topn"

    i = lowest(lambda n: isinstance(n, Distinct))
    if i is not None and path_streams(i):
        return stream, path[i], "distinct"

    # passthrough: the TOPMOST node that itself streams and whose whole
    # lower path streams (the maximal streamable prefix)
    best = None
    for i in range(len(path) - 1):
        node = path[i]
        ok_self = isinstance(node, (Filter, Project)) or (
            isinstance(node, JoinOp) and path[i + 1] is node.left
        )
        if ok_self and path_streams(i):
            best = i
            break
    if best is not None:
        split = path[best]
        est = executor._est_rows(split)
        out_b = est * _row_bytes(output_schema(split))
        if out_b <= budget:
            return stream, split, "passthrough"
        raise NotStreamable("passthrough partials exceed budget")
    # last resort: stream the scan itself (its pushed filter reduces per
    # chunk); everything above — window, sort, set ops — runs on $partials.
    # Partial width counts only the columns the plan reads, matching the
    # narrowed chunk program ChunkedPreparedPlan builds for this kind
    est = executor._est_rows(stream)
    t = executor.catalog[stream.table]
    cols = needed.get(stream.alias) or {t.schema.fields[0].name}
    per_row = max(sum(
        f.dtype.storage_np.itemsize
        for f in t.schema.fields if f.name in cols
    ), 1)
    if est * per_row <= budget:
        return stream, stream, "scan"
    raise NotStreamable("no streamable split above the streamed scan")


def _replace_node(plan: LogicalOp, target: LogicalOp, replacement: LogicalOp):
    if plan is target:
        return replacement
    kids = _children(plan)
    if not kids:
        return plan
    if isinstance(plan, (JoinOp, SetOp)):
        return dc_replace(
            plan,
            left=_replace_node(plan.left, target, replacement),
            right=_replace_node(plan.right, target, replacement),
        )
    return dc_replace(
        plan, child=_replace_node(plan.child, target, replacement)
    )


def _partials_scan(out_s: Schema, alias: str = "$m") -> Scan:
    """Scan($partials) with an extra `$live` int8 column: the relation is
    padded to a stable power-of-two capacity so the merge program's input
    shapes — and therefore its XLA executable — are reused across runs;
    pad rows are filtered by the pushed `$live = 1` predicate."""
    fields = [Field(f"{alias}.{f.name}", f.dtype) for f in out_s.fields]
    fields.append(Field(f"{alias}.$live", DataType.int8()))
    return Scan(
        "$partials", alias, Schema(tuple(fields)),
        pushed_filter=E.Compare("=", E.ColRef(f"{alias}.$live"), E.lit(1)),
    )


def _merge_plan(split: LogicalOp, kind: str, alias: str = "$m"):
    """(chunk_plan, merge_node): the program run per chunk and the node
    that replaces `split` in the surrounding plan, reading $partials.

    agg:         partial = Aggregate output rows; merge = re-aggregate
                 (sum/count->sum, min->min, max->max)
    topn:        partial = top (n+offset) rows per chunk; merge = the
                 original TopN over the concatenated partials
    distinct:    partial = per-chunk dedup; merge = final dedup
    passthrough: partial = the surviving rows themselves; merge = a rename
                 projection (the rest of the plan runs unchanged)
    """
    out_s = output_schema(split)
    scan = _partials_scan(out_s, alias)
    if kind == "agg":
        group_keys = tuple(
            (name, E.ColRef(f"{alias}.{name}"))
            for name, _e in split.group_keys
        )
        aggs = tuple(
            (name, _MERGE_FN[fn], E.ColRef(f"{alias}.{name}"), False)
            for name, fn, _arg, _d in split.aggs
        )
        return split, scan, Aggregate(scan, group_keys, aggs)
    # rename projection: "$m.x" -> "x" so the surrounding plan sees the
    # split node's original output names
    rename = Project(
        scan,
        tuple((f.name, E.ColRef(f"{alias}.{f.name}")) for f in out_s.fields),
    )
    if kind == "topn":
        chunk = dc_replace(split, n=split.n + split.offset, offset=0)
        return chunk, scan, dc_replace(split, child=rename)
    if kind == "distinct":
        return split, scan, Distinct(rename)
    if kind == "passthrough":
        return split, scan, rename
    raise AssertionError(kind)


class _OverlayCatalog:
    """Base catalog plus extra tables (the $partials relation)."""

    def __init__(self, base, extra: dict):
        self.base = base
        self.extra = extra

    def __getitem__(self, name):
        if name in self.extra:
            return self.extra[name]
        return self.base[name]

    def __contains__(self, name):
        return name in self.extra or name in self.base

    def is_private(self, name):
        if name in self.extra:
            return False
        f = getattr(self.base, "is_private", None)
        return f(name) if f is not None else False


class ChunkWindowMixin:
    """Shared chunk-window behavior of the single-chip and PX chunk
    executors: the [start, end) slice state, the host-side slice batch,
    and chunk-sized cardinality estimates. Subclasses provide
    `table_batch` (the device placement differs: plain arrays vs sharded
    device_put)."""

    #: single-chip chunk sources accept prefetch-staged compressed chunks
    #: (engine/pipeline.py); the PX source keeps the legacy host-slice
    #: path (its uploads must shard over the mesh, not ride device_put)
    supports_staged = False

    def set_chunk(self, start: int, end: int):
        self._chunk = (start, end)
        item = getattr(self, "_staged_item", None)
        if item is not None and item.win != (start, end):
            self._staged_item = None
        # drop only the streamed table's cached device batch
        self.invalidate_table(self.stream_table)

    def set_stager(self, stager) -> None:
        """Attach/detach the wire-encoding stager for the streaming run
        (pipeline.run_stream brackets the chunk loop with this)."""
        self._stager = stager
        self._staged_item = None

    def set_chunk_staged(self, start: int, end: int, item) -> None:
        """Position the window on a chunk whose wire-encoded arrays are
        already on device (prefetched): the next table read decodes the
        staged tree instead of re-slicing host arrays."""
        self._staged_item = item
        self.set_chunk(start, end)

    def _chunk_slice_batch(self, name, cols):
        """Host ColumnBatch of the current chunk window, padded to the
        constant chunk capacity (one XLA compile for every chunk).

        Wire discipline (the streaming hot path — the network-attached
        chip moves ~12-30MB/s host->device): integer columns ship
        frame-of-reference NARROWED (min-subtracted, downcast per the
        shared tier rule) and decode in ONE jitted dispatch; per-column
        eager device ops would pay a tunnel round trip each. Tiers
        freeze per column from TABLE-level min/max on first use so the
        decode signature — and with it the chunk program's XLA cache
        entry — stays stable across every chunk; a chunk that falls
        outside the frozen frame (data changed under a cached plan)
        falls back to full width for that chunk, trading one recompile
        for correctness."""
        from ..core.column import ColumnBatch, narrow_tier

        s, e = self._chunk
        item = getattr(self, "_staged_item", None)
        stager = getattr(self, "_stager", None)
        if item is not None and stager is not None \
                and item.win == (s, e):
            # decode-on-device path: the wire-encoded chunk is already on
            # device (prefetched); ONE jitted kernel expands it
            return stager.decode_batch(item, cols)
        t = self.catalog[name]
        sub_schema = Schema(
            tuple(f for f in t.schema.fields if f.name in cols)
        )
        cap = self.chunk_rows
        narrow: dict = {}
        bases: dict = {}
        if not hasattr(self, "_narrow_plan"):
            self._narrow_plan: dict = {}

        def tier_of(key, full, storage):
            hit = self._narrow_plan.get(key)
            if hit is None:
                a = np.asarray(full)
                if (np.dtype(storage).kind in "iu" and a.ndim == 1
                        and len(a)):
                    amin = int(a.min())
                    nt = narrow_tier(
                        amin, int(a.max()), np.dtype(storage).itemsize)
                    hit = (nt, amin) if nt is not None else (None, 0)
                else:
                    hit = (None, 0)
                self._narrow_plan[key] = hit
            return hit

        def add(key, a, storage, full):
            a = np.asarray(a, dtype=storage)
            nt, base = tier_of(key, full, storage)
            if cap > len(a):
                # pad INSIDE the frozen frame (dead rows are masked by
                # sel; zeros would fall below a positive table min and
                # force the full-width fallback on every final chunk)
                padv = base if nt is not None else 0
                a = np.concatenate(
                    [a, np.full((cap - len(a),) + a.shape[1:], padv,
                                dtype=a.dtype)])
            if nt is not None:
                d = a.astype(np.int64) - base
                if 0 <= int(d.min()) and int(d.max()) <= np.iinfo(nt).max:
                    narrow[key] = d.astype(nt)
                    bases[key] = a.dtype.type(base)
                    return
            narrow[key] = a
            if not key.startswith("#v:"):
                bases[key] = a.dtype.type(0)

        for f in sub_schema.fields:
            add(f.name, t.data[f.name][s:e], f.dtype.storage_np,
                t.data[f.name])
        for c, v in t.valid.items():
            if c in cols:
                add(f"#v:{c}", np.asarray(v[s:e], np.uint8), np.uint8, v)
        decoded, sel = _decode_chunk(narrow, bases, e - s)
        dcols = {k: v for k, v in decoded.items() if not k.startswith("#v:")}
        dvalid = {k[3:]: v for k, v in decoded.items() if k.startswith("#v:")}
        return ColumnBatch(
            cols=dcols,
            valid=dvalid,
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=sub_schema,
            dicts={c: d for c, d in t.dicts.items() if c in cols},
        )

    def _est_rows(self, op):
        # the streamed scan sees chunk_rows per execution, not table rows
        if isinstance(op, Scan) and op.table == self.stream_table:
            est = float(self.chunk_rows)
            if op.pushed_filter is not None:
                t = self.catalog[op.table]
                ts = self.stats.table_stats(op.table) if self.stats else None
                if ts is not None and ts.nrows > 0:
                    est *= ts.selectivity(op.pushed_filter, t)
                else:
                    est *= 0.25 ** min(
                        len(self._conjuncts(op.pushed_filter)), 3
                    )
            return max(est, 1.0)
        return super()._est_rows(op)


class _ChunkSourceExecutor(ChunkWindowMixin, Executor):
    """Executor whose streamed table reads one fixed-capacity chunk."""

    supports_staged = True
    chunking_enabled = False
    # chunk windows break the whole-table storage-order premise of the
    # clustered-FK segment aggregation (fk_ranges index full-table rows)
    # and of dynamic-slice range pruning (bounds index full-table rows)
    clustered_agg_enabled = False
    scan_slice_enabled = False

    def __init__(self, catalog, stream_table: str, chunk_rows: int, **kw):
        super().__init__(catalog, **kw)
        self.stream_table = stream_table
        self.chunk_rows = chunk_rows
        self._chunk: tuple[int, int] | None = None

    def table_batch(self, name, cols):
        # the streamed table must NOT ride the per-column device cache
        # (each chunk is a different host slice); every read rebuilds
        # from the current chunk window
        if name == self.stream_table and self._chunk is not None:
            return self._chunk_slice_batch(name, cols)
        return super().table_batch(name, cols)

    def _build_batch(self, name, cols):
        if name != self.stream_table or self._chunk is None:
            return super()._build_batch(name, cols)
        return self._chunk_slice_batch(name, cols)


class ChunkedPreparedPlan:
    """Drop-in replacement for PreparedPlan when inputs exceed the device
    budget: runs the chunk program per chunk, then the merge plan."""

    def __init__(self, executor: Executor, plan: LogicalOp,
                 stream: Scan, split: LogicalOp, kind: str,
                 chunk_rows: int):
        self.executor = executor
        self.plan = plan
        self.stream = stream
        self.split = split
        self.kind = kind
        self.chunk_rows = chunk_rows
        self.retries = 0
        self.stream_stats = StreamStats()

        if kind == "scan":
            # chunk program = the scan narrowed to the raw columns the
            # plan reads; the rename projection restores the scan's
            # qualified output names for the surrounding plan
            t = executor.catalog[stream.table]
            needed = executor._needed_columns(plan).get(stream.alias) or {
                t.schema.fields[0].name
            }
            chunk_plan = Project(
                stream,
                tuple(
                    (c, E.ColRef(f"{stream.alias}.{c}"))
                    for c in sorted(needed)
                ),
            )
            out_s = output_schema(chunk_plan)
            scan2 = _partials_scan(out_s)
            merge_node = Project(
                scan2,
                tuple(
                    (f"{stream.alias}.{f.name}", E.ColRef(f"$m.{f.name}"))
                    for f in out_s.fields
                ),
            )
            self.above_plan = _replace_node(plan, split, merge_node)
            self.partial_schema = out_s
        else:
            chunk_plan, _scan, merge_node = _merge_plan(split, kind)
            self.above_plan = _replace_node(plan, split, merge_node)
            self.partial_schema = output_schema(split)

        self.chunk_exec = executor.make_chunk_source(
            stream.table, chunk_rows
        )
        self.chunk_prepared = self.chunk_exec.prepare(chunk_plan)

        # persistent merge executor: $partials is swapped per run at a
        # grow-only power-of-two capacity so the merge XLA executable is
        # compiled once and reused (review r2: no re-jit per execution)
        self._overlay_extra: dict = {}
        self.merge_exec = Executor(
            _OverlayCatalog(executor.catalog, self._overlay_extra),
            unique_keys=executor.unique_keys, stats=None,
        )
        self.merge_exec.chunking_enabled = False
        self._partial_cap = 1024
        self._merge_prepared = None
        self._merge_cap = 0

    def run_nocheck(self, qparams: tuple = ()):
        return self.run(qparams=qparams)

    def run(self, max_retries: int = 3, qparams: tuple = ()):
        if getattr(self.chunk_exec, "supports_staged", False):
            # streaming pipeline (engine/pipeline.py): prefetch-staged
            # wire-encoded chunks, decode-on-device, overlap metering
            cols, valids, dicts = run_stream(
                self, qparams=qparams, max_retries=max_retries)
        else:
            cols, valids, dicts = self._run_legacy(max_retries, qparams)
        partials, self._partial_cap = assemble_partials_table(
            self.partial_schema, cols, valids, dicts, self._partial_cap)
        self._overlay_extra["$partials"] = partials
        self.merge_exec.invalidate_table("$partials")
        if self._merge_prepared is None or self._merge_cap != self._partial_cap:
            self._merge_prepared = self.merge_exec.prepare(self.above_plan)
            self._merge_cap = self._partial_cap
        return self._merge_prepared.run(max_retries, qparams=qparams)

    def _run_legacy(self, max_retries: int = 3, qparams: tuple = ()):
        import os
        from collections import deque

        import jax

        t = self.executor.catalog[self.stream.table]
        n = t.nrows or 0
        from ..share.interrupt import checkpoint

        # ---- pipelined chunk loop (double buffering) ------------------
        # Dispatch runs DEPTH chunks ahead of the draining fetch: while
        # the host decodes/accumulates chunk k's partial, the device is
        # already computing k+1 and the wire is carrying k+2's upload —
        # the H2D tunnel (~12-30MB/s) and device compute overlap instead
        # of alternating (r4 verdict weak #3: SF100 streaming was fully
        # serialized on the wire). Each drain is ONE device_get.
        depth = max(1, int(os.environ.get("OB_STREAM_PIPELINE", "2")))
        if depth > 1 and n:
            # the pipeline holds `depth` chunk slices on device at once;
            # the split's budget math sized ONE chunk — cap depth so the
            # in-flight residency stays inside the device budget (review)
            needed = self.executor._needed_columns(self.plan).get(
                self.stream.alias
            ) or set()
            per_row = max(1, sum(
                self.executor.catalog[self.stream.table].schema[c]
                .storage_np.itemsize
                for c in needed
            )) if needed else 8
            chunk_bytes = per_row * self.chunk_rows
            fit = max(1, int(self.executor.device_budget * 0.5)
                      // max(chunk_bytes, 1))
            depth = max(1, min(depth, fit))
        windows: deque = deque()
        s = 0
        while s < n:
            e = min(s + self.chunk_rows, n)
            windows.append((s, e))
            s = e
        if n == 0:
            windows.append((0, 0))
        pending: deque = deque()  # (s, e, gen, out, ovf_dev)
        attempts_of: dict = {}
        params_gen = 0  # bumps once per recompile (review: two in-flight
        # chunks overflowing the same node must not DOUBLE-bump capacities)
        cols: dict[str, list] = {f.name: [] for f in self.partial_schema.fields}
        valids: dict[str, list] = {}
        dicts = {}

        def dispatch(win):
            ws, we = win
            self.chunk_exec.set_chunk(ws, we)
            out, ovf = self.chunk_prepared.jitted(
                self.chunk_prepared._inputs(), qparams)
            pending.append((ws, we, params_gen, out, ovf))

        while windows or pending:
            checkpoint()  # a killed query stops between chunks
            while windows and len(pending) < depth:
                dispatch(windows.popleft())
            ws, we, gen, out, ovf = pending.popleft()
            fetch_cols = {
                f.name: out.cols[f.name] for f in self.partial_schema.fields
            }
            fetch_valid = {
                k: v for k, v in out.valid.items()
                if k in fetch_cols
            }
            hovf, hcols, hvalid, hsel = jax.device_get(
                (ovf, fetch_cols, fetch_valid, out.sel))
            overflows = self.chunk_prepared._overflows(np.asarray(hovf))
            if overflows:
                if gen == params_gen:
                    # first overflow since the last recompile: bump and
                    # rebuild. Only THIS path consumes a retry attempt —
                    # a sibling chunk dispatched pre-bump re-runs on the
                    # grown capacities for free (its overflow may already
                    # be covered; capacities grow monotonically, so the
                    # loop always progresses)
                    a = attempts_of.get(ws, 0)
                    if a >= max_retries:
                        raise RuntimeError(
                            f"chunk [{ws},{we}) capacity overflow after "
                            f"{max_retries} retries: {overflows}")
                    attempts_of[ws] = a + 1
                    self.retries += 1
                    self.chunk_prepared.retries += 1
                    self.chunk_prepared.params.bump(overflows)
                    (self.chunk_prepared.jitted,
                     self.chunk_prepared.input_spec,
                     self.chunk_prepared.overflow_nodes) = (
                        self.chunk_prepared.executor.compile(
                            self.chunk_prepared.plan,
                            self.chunk_prepared.params))
                    params_gen += 1
                # in-flight chunks used the SMALL capacities: their own
                # counters decide their fate when drained; this chunk
                # re-dispatches at the head of the queue
                windows.appendleft((ws, we))
                continue
            sel = np.asarray(hsel)
            for f in self.partial_schema.fields:
                cols[f.name].append(np.asarray(hcols[f.name])[sel])
                v = hvalid.get(f.name)
                if v is not None:
                    valids.setdefault(f.name, []).append(np.asarray(v)[sel])
                elif f.name in valids:
                    valids[f.name].append(np.ones(int(sel.sum()), np.bool_))
            dicts.update(out.dicts)

        return cols, valids, dicts
