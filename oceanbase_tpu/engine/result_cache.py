"""Device-resident result cache: the tier ABOVE the plan cache.

The statement fast path (engine/session.py) already skips parse, resolve,
plan and compile for a warm statement; what remains per hit is bind +
dispatch + the completion sync. For the repeated-dashboard shape — the
same normalized text with the same bound literals against unchanged
tables — even that is redundant: the narrowed result frame the fused
program produced last time is still exactly the answer. This cache holds
those frames, keyed like the fast tier plus the bound literals and a
snapshot watermark, so a repeat serves decoded host columns with ZERO
device dispatches.

Identity = (logical entry key, bound literal values, snapshot watermark):
- the logical key embeds schema + dictionary versions via key_extra, so a
  schema bump or dictionary growth changes the key (never a stale serve);
- the watermark is the referenced tables' committed data versions (the
  server wires it), so committed DML changes the key;
- DML/flush additionally REMOVE entries eagerly (invalidate_tables /
  flush) — the key change alone would strand dead frames at capacity.

Each entry keeps a reference to the NarrowDeviceResult cursor that
produced it, pinning the ncap-row frame on device: the cache is charged
against the tenant's memory unit through the governor residency surface
(server/database.py _resident_bytes) and drops its pins under the same
OOM/eviction ladder as cold table residency (rung 1 flushes it first —
cached results are the most re-creatable bytes on the chip).
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class ResultEntry:
    """One cached narrowed result: decoded host columns (hits pay no
    fold work) + the device frame pin via the producing cursor."""

    __slots__ = ("names", "columns", "nbytes", "tables", "cursor", "hits")

    def __init__(self, names, columns, nbytes, tables, cursor=None):
        self.names = tuple(names)
        self.columns = columns
        self.nbytes = int(nbytes)
        self.tables = tuple(tables)
        self.cursor = cursor
        self.hits = 0

    def copy_columns(self) -> dict:
        """Defensive per-serve copy: clients may mutate result arrays in
        place, and a shared reference would corrupt every later hit."""
        out = {}
        for n, v in self.columns.items():
            if isinstance(v, list):
                out[n] = list(v)
            elif hasattr(v, "copy"):
                out[n] = v.copy()
            else:
                out[n] = v
        return out


def _copy_columns(columns: dict) -> dict:
    return ResultEntry((), columns, 0, ()).copy_columns()


class ResultCache:
    """LRU by bytes with a per-table inverted index for DML invalidation.

    Thread-safe: server sessions probe/admit concurrently. Unhashable
    keys (a statement bound an unhashable literal) degrade to a miss /
    no-admit instead of failing the statement."""

    def __init__(self, capacity_bytes: int = 4 << 20,
                 entry_limit: int = 65536, enabled_fn=None,
                 pressure_fn=None, metrics=None):
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._by_table: dict[str, set] = {}
        self.capacity_bytes = int(capacity_bytes)
        self.entry_limit = int(entry_limit)
        # hook: ob_enable_result_cache (session checks before keying)
        self.enabled_fn = enabled_fn
        # hook: governor under_pressure — a pressured tenant must not
        # grow its device pins for a speculative cache admit
        self.pressure_fn = pressure_fn
        self.metrics = metrics
        self.bytes_used = 0
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.invalidations = 0

    # ------------------------------------------------------------ knobs
    def enabled(self) -> bool:
        fn = self.enabled_fn
        return bool(fn()) if fn is not None else True

    def _count(self, name: str) -> None:
        m = self.metrics
        if m is not None and m.enabled:
            m.add(name)

    # ------------------------------------------------------------ probe
    def get(self, key):
        with self._lock:
            try:
                e = self._entries.get(key)
            except TypeError:
                e = None
            if e is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                self.hits += 1
                e.hits += 1
        self._count("result cache hits" if e is not None
                    else "result cache misses")
        return e

    # ------------------------------------------------------------ admit
    def put(self, key, names, columns, nbytes, tables, cursor=None) -> bool:
        nbytes = int(nbytes)
        if nbytes > self.entry_limit or nbytes > self.capacity_bytes:
            return False
        pf = self.pressure_fn
        if pf is not None and pf():
            self._count("result cache admit refused: pressure")
            return False
        entry = ResultEntry(names, _copy_columns(columns), nbytes, tables,
                            cursor=cursor)
        with self._lock:
            try:
                old = self._entries.pop(key, None)
            except TypeError:
                return False
            if old is not None:
                self._forget(key, old)
            self._entries[key] = entry
            self.bytes_used += nbytes
            for t in entry.tables:
                self._by_table.setdefault(t, set()).add(key)
            self.puts += 1
            while self.bytes_used > self.capacity_bytes and self._entries:
                k2, e2 = self._entries.popitem(last=False)
                self._forget(k2, e2)
                self.evictions += 1
        self._count("result cache puts")
        return True

    def _forget(self, key, e) -> None:
        # lock held: undo one entry's byte + index accounting
        self.bytes_used -= e.nbytes
        for t in e.tables:
            s = self._by_table.get(t)
            if s is not None:
                s.discard(key)
                if not s:
                    del self._by_table[t]

    # ------------------------------------------------------- invalidate
    def invalidate_tables(self, tables) -> int:
        """Eager drop of every entry touching any of `tables` (committed
        DML, schema change). Returns the number dropped."""
        n = 0
        with self._lock:
            keys = set()
            for t in tables:
                keys |= self._by_table.get(t, set())
            for k in keys:
                e = self._entries.pop(k, None)
                if e is not None:
                    self._forget(k, e)
                    n += 1
            self.invalidations += n
        if n:
            self._count("result cache invalidations")
        return n

    def flush(self) -> int:
        """Drop everything (plan-cache flush, OOM eviction rung)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._by_table.clear()
            self.bytes_used = 0
            self.invalidations += n
        return n

    # ---------------------------------------------------- observability
    def device_bytes(self) -> int:
        """Device-pinned frame bytes (governor residency charge). The
        narrowed frame mirrors the host copy byte-for-byte, so the host
        accounting doubles as the device charge."""
        return self.bytes_used

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes_used": self.bytes_used,
                "capacity_bytes": self.capacity_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "puts": self.puts,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
            }

    def rows(self):
        """(tables, nrows, nbytes, hits) per entry, LRU->MRU — the
        __all_virtual_result_cache surface."""
        with self._lock:
            out = []
            for e in self._entries.values():
                nrows = 0
                if e.names:
                    nrows = len(e.columns[e.names[0]])
                out.append((",".join(e.tables), nrows, e.nbytes, e.hits))
            return out
