"""Physical codegen + execution: logical plan -> one jitted XLA program.

Reference surface: the code generator (ObStaticEngineCG,
sql/code_generator/ob_static_engine_cg.h:185) that lowers the logical plan
to an ObOpSpec tree, plus the ObOperator::get_next_batch driver loop
(sql/engine/ob_operator.cpp:1425). The TPU redesign collapses the operator
pull-loop entirely: the whole plan (or later, each DFO) traces into ONE XLA
computation over table ColumnBatches — scan masks, join gathers, group-by
scatters, sort permutations all fuse into a single device program, which is
the idiomatic TPU replacement for per-batch virtual dispatch.

Static-shape discipline (the ObBatchRows analog): every intermediate keeps
its producer's capacity with a live-row `sel` mask. Operators that change
cardinality (expand joins, group-bys) emit into planner-chosen static
capacities and return overflow counters; the host driver checks the
counters and re-executes with larger capacities (the TPU analog of the
reference's spill-to-disk: respill-to-a-larger-compile).

Physical choices made here (the optimizer's physical half):
- join: unique-build hash join when the build side's key covers a declared
  unique key of its base table; expand (sort+searchsorted) join otherwise.
- group-by: direct-addressed scatter when all keys are small-domain
  dictionary/bounded columns (packed perfect hash); open-addressing hash
  table otherwise (the reference's adaptive bypass, chosen statically).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import jax
import jax.numpy as jnp
import numpy as np

from ..core.column import ColumnBatch, batch_to_host
from ..core.dtypes import DataType, Field, Schema, TypeKind
from ..expr import ir as E
from ..expr.compile import (
    compile_predicate,
    derive_dict_column,
    evaluate,
    infer_type,
)
from ..ops.hashagg import assign_group_slots, _apply_agg
from ..ops.hashing import next_pow2, pack_keys
from ..ops.join import (
    build_hash_table,
    expand_join,
    hash_join_probe,
    join_keys64,
    sort_build_side,
)
from ..ops.sort import sort_indices
from ..sql.logical import (
    Aggregate,
    Distinct,
    Filter,
    JoinOp,
    Limit,
    LogicalOp,
    Project,
    Scan,
    Sort,
    output_schema,
)

DIRECT_GROUPBY_MAX_DOMAIN = 1 << 12


@dataclass
class PhysicalParams:
    """Static capacities per plan node (keyed by pre-order node index;
    exchange lanes use synthesized ids, see parallel/px.py)."""

    groupby_size: dict[int, int] = field(default_factory=dict)
    join_cap: dict[int, int] = field(default_factory=dict)
    exchange_cap: dict[int, int] = field(default_factory=dict)

    def bump(self, overflows: dict[int, int]):
        for nid in overflows:
            if nid in self.groupby_size:
                self.groupby_size[nid] *= 4
            if nid in self.join_cap:
                self.join_cap[nid] *= 4
            if nid in self.exchange_cap:
                self.exchange_cap[nid] *= 4


def _number_nodes(plan: LogicalOp) -> dict[int, LogicalOp]:
    out = {}

    def rec(op):
        out[len(out)] = op
        for c in _children(op):
            rec(c)

    rec(plan)
    return out


def _children(op: LogicalOp):
    if isinstance(op, (Filter, Project, Sort, Limit, Distinct, Aggregate)):
        return [op.child]
    if isinstance(op, JoinOp):
        return [op.left, op.right]
    return []


def _dict_domain(batch: ColumnBatch, e: E.Expr) -> int | None:
    """Static domain size of a group key expr (dict columns, bools)."""
    if isinstance(e, E.ColRef):
        d = batch.dicts.get(e.name)
        if d is not None:
            return len(d)
        t = batch.schema[e.name]
        if t.kind is TypeKind.BOOL:
            return 2
        if t.kind is TypeKind.INT8:
            return 256
    return None


class Executor:
    def __init__(self, catalog, unique_keys=None, default_rows_estimate=1 << 16,
                 stats=None):
        self.catalog = catalog
        self.unique_keys = unique_keys or {}
        self.default_rows_estimate = default_rows_estimate
        # share/stats.StatsManager: NDV/histogram-backed cardinalities for
        # static capacities (None = heuristic constants)
        self.stats = stats
        self._batch_cache: dict[tuple[str, tuple], ColumnBatch] = {}

    # ---- input preparation -------------------------------------------
    def _collect_scans(self, plan: LogicalOp) -> list[Scan]:
        out = []

        def rec(op):
            if isinstance(op, Scan):
                out.append(op)
            for c in _children(op):
                rec(c)

        rec(plan)
        return out

    def _needed_columns(self, plan: LogicalOp) -> dict[str, set[str]]:
        """alias -> set of unqualified column names referenced anywhere."""
        needed: dict[str, set[str]] = {}

        def note(e: E.Expr):
            for q in E.referenced_columns(e):
                if "." in q:
                    a, c = q.split(".", 1)
                    needed.setdefault(a, set()).add(c)

        def rec(op):
            if isinstance(op, Scan) and op.pushed_filter is not None:
                note(op.pushed_filter)
            if isinstance(op, Filter):
                note(op.pred)
            if isinstance(op, Project):
                for _, e in op.exprs:
                    note(e)
            if isinstance(op, JoinOp):
                for e in op.left_keys + op.right_keys:
                    note(e)
                if op.residual is not None:
                    note(op.residual)
            if isinstance(op, Aggregate):
                for _, e in op.group_keys:
                    note(e)
                for _, _, a, _ in op.aggs:
                    if a is not None:
                        note(a)
            if isinstance(op, Sort):
                for e, _ in op.keys:
                    note(e)
            for c in _children(op):
                rec(c)

        rec(plan)
        return needed

    def invalidate_table(self, name: str) -> None:
        """Drop cached device batches of one table (its data changed)."""
        for key in [k for k in self._batch_cache if k[0] == name]:
            del self._batch_cache[key]

    def table_batch(self, name: str, cols: tuple[str, ...]) -> ColumnBatch:
        is_private = getattr(self.catalog, "is_private", None)
        if is_private is not None and is_private(name):
            # tx-private view: never enters (or reads) the shared device
            # cache, so other sessions can't see uncommitted rows
            return self._build_batch(name, cols)
        key = (name, cols)
        if key not in self._batch_cache:
            self._batch_cache[key] = self._build_batch(name, cols)
        return self._batch_cache[key]

    def _build_batch(self, name: str, cols: tuple[str, ...]) -> ColumnBatch:
        t = self.catalog[name]
        sub_schema = Schema(
            tuple(f for f in t.schema.fields if f.name in cols)
        )
        from ..core.column import make_batch

        return make_batch(
            {c: t.data[c] for c in sub_schema.names()},
            sub_schema,
            {c: d for c, d in t.dicts.items() if c in cols},
            valid={c: v for c, v in t.valid.items() if c in cols},
        )

    # ---- physical parameter seeding ----------------------------------
    def _est_rows(self, op) -> float:
        """Cardinality estimate driving static capacities (and the PX
        layer's distribution-method choice)."""
        est_rows = self._est_rows
        if isinstance(op, Scan):
            t = self.catalog[op.table]
            base = t.nrows or 1
            if op.pushed_filter is not None:
                ts = self.stats.table_stats(op.table) if self.stats else None
                if ts is not None and ts.nrows > 0:
                    base *= ts.selectivity(op.pushed_filter, t)
                else:
                    base *= 0.25 ** min(
                        len(self._conjuncts(op.pushed_filter)), 3
                    )
            return max(base, 1.0)
        if isinstance(op, Filter):
            return max(est_rows(op.child) * 0.5, 1.0)
        if isinstance(op, JoinOp):
            l = est_rows(op.left)
            r = est_rows(op.right)
            if op.kind in ("semi", "anti"):
                return max(l * 0.5, 1.0)
            if op.kind == "left":
                return l * 2
            if not op.left_keys:  # cross / scalar broadcast
                return l if self._is_scalar_relation(op.right) else l * r
            if self._join_build_unique(op):
                return l
            # M:N equi-join: |L||R| / max(ndv(Lkeys), ndv(Rkeys)) — the
            # textbook containment estimate (ob_opt_selectivity analog)
            lndv = self._keys_ndv(op.left, op.left_keys)
            rndv = self._keys_ndv(op.right, op.right_keys)
            if lndv is not None and rndv is not None:
                denom = max(min(lndv, l), min(rndv, r), 1.0)
                return max((l * r) / denom, 1.0)
            return max(l, r) * 2
        if isinstance(op, Aggregate):
            child = est_rows(op.child)
            nd = self._group_ndv(op)
            if nd is not None:
                return max(min(child, nd), 1.0)
            return min(child, float(self.default_rows_estimate))
        if isinstance(op, (Project, Sort, Distinct)):
            return est_rows(op.child)
        if isinstance(op, Limit):
            return float(op.n + op.offset)
        return float(self.default_rows_estimate)

    def seed_params(self, plan: LogicalOp) -> PhysicalParams:
        params = PhysicalParams()
        nodes = _number_nodes(plan)
        est_rows = self._est_rows

        for nid, op in nodes.items():
            if isinstance(op, Aggregate):
                # hash-table capacity: group-count estimate when NDV stats
                # resolve (margin absorbs sampling error), else child rows
                nd = self._group_ndv(op)
                target = (
                    min(est_rows(op.child), nd * 1.5 + 64)
                    if nd is not None else est_rows(op.child)
                )
                params.groupby_size[nid] = next_pow2(
                    int(2 * min(target, 1 << 21)) + 16
                )
            if isinstance(op, Distinct):
                params.groupby_size[nid] = next_pow2(
                    int(2 * min(est_rows(op.child), 1 << 21)) + 16
                )
            if isinstance(op, JoinOp):
                needs_cap = (
                    (op.kind in ("inner", "cross") and not self._join_build_unique(op))
                    or (op.kind in ("semi", "anti") and op.residual is not None)
                    or op.kind == "left"
                )
                if needs_cap:
                    if op.kind in ("semi", "anti", "left"):
                        # candidate-pair capacity, not output rows
                        cap = int(
                            max(est_rows(op.left), est_rows(op.right)) * 2
                        ) + 1024
                    else:
                        cap = int(est_rows(op)) * 2 + 1024
                    params.join_cap[nid] = -(-cap // 1024) * 1024
        return params

    @staticmethod
    def _conjuncts(e):
        from ..sql.planner import split_conjuncts

        return split_conjuncts(e)

    def _keys_ndv(self, side: LogicalOp, keys) -> float | None:
        """Product of base-column NDVs for join keys resolvable to scans of
        `side` (None when any key isn't a plain column or stats are off)."""
        if self.stats is None:
            return None
        amap = {s.alias: s.table for s in self._collect_scans(side)}
        prod = 1.0
        for k in keys:
            if not isinstance(k, E.ColRef) or "." not in k.name:
                return None
            a, c = k.name.split(".", 1)
            tname = amap.get(a)
            if tname is None:
                return None
            ts = self.stats.table_stats(tname)
            nd = ts.ndv_of(c) if ts is not None else None
            if nd is None or nd <= 0:
                return None
            prod *= nd
        return prod

    def _group_ndv(self, op: Aggregate) -> float | None:
        """Product of group-key NDVs (grouping cardinality upper bound)."""
        if self.stats is None or not op.group_keys:
            return None
        prod = 1.0
        amap = {s.alias: s.table for s in self._collect_scans(op.child)}
        for _name, e in op.group_keys:
            if not isinstance(e, E.ColRef) or "." not in e.name:
                return None
            a, c = e.name.split(".", 1)
            tname = amap.get(a)
            if tname is None:
                return None
            ts = self.stats.table_stats(tname)
            nd = ts.ndv_of(c) if ts is not None else None
            if nd is None or nd <= 0:
                return None
            prod *= nd
        return prod

    @staticmethod
    def _is_scalar_relation(node: LogicalOp) -> bool:
        """True for a guaranteed-1-row relation (grand aggregate, possibly
        under projections/filters) — the broadcast side of a scalar-subquery
        join."""
        while isinstance(node, (Filter, Project)):
            node = node.child
        return isinstance(node, Aggregate) and not node.group_keys

    def _join_build_unique(self, op: JoinOp) -> bool:
        """True if the build (right) side's join keys cover a unique key of
        its source: a base table's declared unique key, an Aggregate's full
        group-key set, or a Distinct's full column set — seen through
        Filter/Project (renames followed)."""
        if self._is_scalar_relation(op.right):
            return True
        names = []
        for e in op.right_keys:
            if not isinstance(e, E.ColRef):
                return False
            names.append(e.name)
        node = op.right
        while True:
            if isinstance(node, Filter):
                node = node.child
            elif isinstance(node, Project):
                rename = {n: ex for n, ex in node.exprs}
                nxt = []
                for n in names:
                    ex = rename.get(n)
                    if not isinstance(ex, E.ColRef):
                        return False
                    nxt.append(ex.name)
                names = nxt
                node = node.child
            else:
                break
        if isinstance(node, Aggregate):
            gk = {n for n, _ in node.group_keys}
            return bool(gk) and gk <= set(names)
        if isinstance(node, Distinct):
            cols = set(output_schema(node).names())
            return cols <= set(names)
        if isinstance(node, Scan):
            uks = self.unique_keys.get(node.table, ())
            key_cols = {
                n.split(".", 1)[1] for n in names if n.startswith(node.alias + ".")
            }
            return any(set(uk) <= key_cols for uk in uks)
        return False

    # ---- tracing ------------------------------------------------------
    def compile(self, plan: LogicalOp, params: PhysicalParams):
        nodes = _number_nodes(plan)
        id_of = {id(op): nid for nid, op in nodes.items()}
        needed = self._needed_columns(plan)
        # make sure every scan uploads at least one column (for row count)
        scans = self._collect_scans(plan)
        input_spec = []
        for s in scans:
            cols = needed.get(s.alias, set())
            if not cols:
                cols = {self.catalog[s.table].schema.fields[0].name}
            input_spec.append((s.alias, s.table, tuple(sorted(cols))))

        overflow_nodes: list[int] = sorted(
            set(params.groupby_size) | set(params.join_cap)
        )

        def emit(op, inputs) -> tuple[ColumnBatch, dict[int, jnp.ndarray]]:
            return self._emit_node(op, inputs, emit, params, id_of)

        def run(inputs: dict[str, ColumnBatch], qparams: tuple = ()):
            from ..expr import compile as expr_compile

            prev = expr_compile.set_params(qparams if qparams else None)
            try:
                out, ovf = emit(plan, inputs)
            finally:
                expr_compile.set_params(prev)
            ovf_vec = [
                ovf.get(nid, jnp.zeros((), jnp.int64)) for nid in overflow_nodes
            ]
            return out, ovf_vec

        return jax.jit(run), input_spec, overflow_nodes

    def _emit_node(self, op, inputs, emit, params, id_of):
        """Emit one plan node into the traced program (dispatch shared by
        the single-chip and PX executors)."""
        nid = id_of[id(op)]
        if isinstance(op, Scan):
            b = inputs[op.alias]
            # qualify names
            qschema = Schema(
                tuple(
                    Field(f"{op.alias}.{f.name}", f.dtype)
                    for f in b.schema.fields
                )
            )
            qb = ColumnBatch(
                cols={f"{op.alias}.{n}": c for n, c in b.cols.items()},
                valid={f"{op.alias}.{n}": v for n, v in b.valid.items()},
                sel=b.sel,
                nrows=b.nrows,
                schema=qschema,
                dicts={f"{op.alias}.{n}": d for n, d in b.dicts.items()},
            )
            if op.pushed_filter is not None:
                qb = qb.with_sel(compile_predicate(op.pushed_filter, qb))
            return qb, {}

        if isinstance(op, Filter):
            child, ovf = emit(op.child, inputs)
            return child.with_sel(compile_predicate(op.pred, child)), ovf

        if isinstance(op, Project):
            child, ovf = emit(op.child, inputs)
            cols, valid, dicts, fields = {}, {}, {}, []
            for name, e in op.exprs:
                derived = derive_dict_column(e, child)
                if derived is not None:
                    # string transform (substr): new dict column
                    v, vv, d2 = derived
                    dicts[name] = d2
                else:
                    v, vv = evaluate(e, child)
                cols[name] = v
                if vv is not None:
                    valid[name] = vv
                t = infer_type(e, child.schema)
                fields.append(Field(name, t))
                if isinstance(e, E.ColRef) and e.name in child.dicts:
                    dicts[name] = child.dicts[e.name]
            return (
                ColumnBatch(
                    cols=cols,
                    valid=valid,
                    sel=child.sel,
                    nrows=child.nrows,
                    schema=Schema(tuple(fields)),
                    dicts=dicts,
                ),
                ovf,
            )

        if isinstance(op, JoinOp):
            return self._emit_join(op, nid, inputs, emit, params)

        if isinstance(op, Aggregate):
            return self._emit_aggregate(op, nid, inputs, emit, params)

        if isinstance(op, Distinct):
            child, ovf = emit(op.child, inputs)
            keys = [child.cols[n] for n in child.schema.names()]
            ts = params.groupby_size[nid]
            row_slot, slot_used, slot_row = assign_group_slots(
                keys, child.sel, ts
            )
            pend = jnp.sum(
                child.sel & (row_slot < 0), dtype=jnp.int64
            )
            n = keys[0].shape[0]
            rep = jnp.clip(slot_row, 0, n - 1)
            cols = {
                name: jnp.where(slot_used, child.cols[name][rep], 0)
                for name in child.schema.names()
            }
            out = ColumnBatch(
                cols=cols,
                valid={},
                sel=slot_used,
                nrows=jnp.sum(slot_used, dtype=jnp.int64),
                schema=child.schema,
                dicts=child.dicts,
            )
            ovf = dict(ovf)
            ovf[nid] = pend
            return out, ovf

        if isinstance(op, Sort):
            child, ovf = emit(op.child, inputs)
            keys, desc = [], []
            for e, d in op.keys:
                v, _ = evaluate(e, child)
                keys.append(v)
                desc.append(d)
            order = sort_indices(keys, desc, child.sel)
            cols = {n: c[order] for n, c in child.cols.items()}
            valid = {n: v[order] for n, v in child.valid.items()}
            return (
                replace(
                    child,
                    cols=cols,
                    valid=valid,
                    sel=child.sel[order],
                ),
                ovf,
            )

        if isinstance(op, Limit):
            child, ovf = emit(op.child, inputs)
            pos = jnp.cumsum(child.sel.astype(jnp.int64)) - 1
            keep = (
                child.sel
                & (pos >= op.offset)
                & (pos < op.offset + op.n)
            )
            return child.with_sel(keep), ovf

        raise NotImplementedError(type(op))

    # ---- join emission -------------------------------------------------
    def _emit_join(self, op: JoinOp, nid, inputs, emit, params):
        if op.kind in ("semi", "anti"):
            return self._emit_semi_anti(op, nid, inputs, emit, params)
        if op.kind == "left":
            return self._emit_left(op, nid, inputs, emit, params)
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ovf = {**lovf, **rovf}
        lkeys = [evaluate(e, left)[0] for e in op.left_keys]
        rkeys = [evaluate(e, right)[0] for e in op.right_keys]
        if not lkeys:
            # cross join: constant key makes every probe row match every
            # build row; a 1-row build (scalar subquery) rides the unique
            # hash path as a broadcast, general cross uses expand
            lkeys = [jnp.zeros(left.capacity, dtype=jnp.int32)]
            rkeys = [jnp.zeros(right.capacity, dtype=jnp.int32)]
        merged_dicts = {**left.dicts, **right.dicts}

        if self._join_build_unique(op):
            nb = rkeys[0].shape[0] if rkeys else right.capacity
            ts = next_pow2(max(2 * nb, 16))
            slot_key, slot_row = build_hash_table(rkeys, right.sel, ts)
            match = hash_join_probe(slot_key, slot_row, rkeys, lkeys, left.sel)
            sel = left.sel & (match >= 0)
            idx = jnp.clip(match, 0, None)
            cols = dict(left.cols)
            valid = dict(left.valid)
            for n, c in right.cols.items():
                cols[n] = c[idx]
            for n, v in right.valid.items():
                valid[n] = v[idx]
            out_schema = _join_schema(left.schema, right.schema)
            out = ColumnBatch(
                cols=cols,
                valid=valid,
                sel=sel,
                nrows=jnp.sum(sel, dtype=jnp.int64),
                schema=out_schema,
                dicts=merged_dicts,
            )
        else:
            cap = params.join_cap[nid]
            skeys, order = sort_build_side(rkeys, right.sel)
            pr, br, valid_rows, total = expand_join(
                skeys, order, right.nrows, lkeys, left.sel, cap
            )
            cols = {}
            valid = {}
            for n, c in left.cols.items():
                cols[n] = c[pr]
            for n, v in left.valid.items():
                valid[n] = v[pr]
            for n, c in right.cols.items():
                cols[n] = c[br]
            for n, v in right.valid.items():
                valid[n] = v[br]
            sel = valid_rows
            # multi-column keys ride a hash: exact-verify the expansion
            if len(op.left_keys) > 1:
                for le, re_ in zip(op.left_keys, op.right_keys):
                    lv, _ = evaluate(le, left)
                    rv, _ = evaluate(re_, right)
                    sel = sel & (lv[pr] == rv[br])
            out_schema = _join_schema(left.schema, right.schema)
            out = ColumnBatch(
                cols=cols,
                valid=valid,
                sel=sel,
                nrows=jnp.sum(sel, dtype=jnp.int64),
                schema=out_schema,
                dicts=merged_dicts,
            )
            ovf = dict(ovf)
            ovf[nid] = jnp.maximum(total - cap, 0)
        if op.residual is not None:
            out = out.with_sel(compile_predicate(op.residual, out))
        return out, ovf

    def _emit_semi_anti(self, op: JoinOp, nid, inputs, emit, params):
        """Semi/anti join: output = left rows with (without) a matching right
        row. No residual: a single hash-probe existence test (duplicate build
        keys are fine — one witness per key suffices, and the probe
        exact-verifies key columns). With residual: expand candidate pairs,
        evaluate the residual per pair, scatter-or a has-match bit per left
        row."""
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ovf = {**lovf, **rovf}
        lkeys = [evaluate(e, left)[0] for e in op.left_keys]
        rkeys = [evaluate(e, right)[0] for e in op.right_keys]
        if op.residual is None:
            nb = rkeys[0].shape[0]
            ts = next_pow2(max(2 * nb, 16))
            slot_key, slot_row = build_hash_table(rkeys, right.sel, ts)
            match = hash_join_probe(slot_key, slot_row, rkeys, lkeys, left.sel)
            has = match >= 0
        else:
            cap = params.join_cap[nid]
            skeys, order = sort_build_side(rkeys, right.sel)
            pr, br, valid_rows, total = expand_join(
                skeys, order, right.nrows, lkeys, left.sel, cap
            )
            pair_sel = valid_rows
            if len(op.left_keys) > 1:
                for le, re_ in zip(op.left_keys, op.right_keys):
                    lv, _ = evaluate(le, left)
                    rv, _ = evaluate(re_, right)
                    pair_sel = pair_sel & (lv[pr] == rv[br])
            # pair batch: left cols gathered by pr, right cols by br
            pair_cols = {n: c[pr] for n, c in left.cols.items()}
            pair_cols.update({n: c[br] for n, c in right.cols.items()})
            pair_valid = {n: v[pr] for n, v in left.valid.items()}
            pair_valid.update({n: v[br] for n, v in right.valid.items()})
            pair_batch = ColumnBatch(
                cols=pair_cols,
                valid=pair_valid,
                sel=pair_sel,
                nrows=jnp.sum(pair_sel, dtype=jnp.int64),
                schema=_join_schema(left.schema, right.schema),
                dicts={**left.dicts, **right.dicts},
            )
            pair_ok = compile_predicate(op.residual, pair_batch)
            n = left.capacity
            has = (
                jnp.zeros(n, dtype=jnp.bool_)
                .at[pr]
                .max(pair_ok, mode="drop")
            )
            ovf = dict(ovf)
            ovf[nid] = jnp.maximum(total - cap, 0)
        sel = left.sel & (has if op.kind == "semi" else ~has)
        return left.with_sel(sel), ovf

    def _emit_left(self, op: JoinOp, nid, inputs, emit, params):
        """Left outer join via expansion: matched pairs plus, appended at a
        left-capacity tail, one all-NULL-right row for every unmatched left
        row. Right columns gain validity masks (they are nullable now)."""
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ovf = {**lovf, **rovf}
        lkeys = [evaluate(e, left)[0] for e in op.left_keys]
        rkeys = [evaluate(e, right)[0] for e in op.right_keys]
        cap = params.join_cap[nid]
        skeys, order = sort_build_side(rkeys, right.sel)
        pr, br, valid_rows, total = expand_join(
            skeys, order, right.nrows, lkeys, left.sel, cap
        )
        pair_sel = valid_rows
        if len(op.left_keys) > 1:
            for le, re_ in zip(op.left_keys, op.right_keys):
                lv, _ = evaluate(le, left)
                rv, _ = evaluate(re_, right)
                pair_sel = pair_sel & (lv[pr] == rv[br])
        merged_dicts = {**left.dicts, **right.dicts}
        if op.residual is not None:
            pair_cols = {n: c[pr] for n, c in left.cols.items()}
            pair_cols.update({n: c[br] for n, c in right.cols.items()})
            pair_valid = {n: v[pr] for n, v in left.valid.items()}
            pair_valid.update({n: v[br] for n, v in right.valid.items()})
            pair_batch = ColumnBatch(
                cols=pair_cols,
                valid=pair_valid,
                sel=pair_sel,
                nrows=jnp.sum(pair_sel, dtype=jnp.int64),
                schema=_join_schema(left.schema, right.schema),
                dicts=merged_dicts,
            )
            pair_sel = compile_predicate(op.residual, pair_batch)
        nl = left.capacity
        has = jnp.zeros(nl, dtype=jnp.bool_).at[pr].max(pair_sel, mode="drop")
        # output = [cap matched-pair slots] ++ [nl unmatched-left slots]
        cols, valid = {}, {}
        for n, c in left.cols.items():
            cols[n] = jnp.concatenate([c[pr], c])
        for n, v in left.valid.items():
            valid[n] = jnp.concatenate([v[pr], v])
        for n, c in right.cols.items():
            cols[n] = jnp.concatenate([c[br], jnp.zeros_like(c, shape=(nl,))])
            rv = right.valid.get(n)
            matched_valid = rv[br] if rv is not None else jnp.ones(cap, jnp.bool_)
            valid[n] = jnp.concatenate([matched_valid, jnp.zeros(nl, jnp.bool_)])
        sel = jnp.concatenate([pair_sel, left.sel & ~has])
        rs_nullable = Schema(
            tuple(
                Field(f.name, f.dtype.with_nullable(True))
                for f in right.schema.fields
            )
        )
        out = ColumnBatch(
            cols=cols,
            valid=valid,
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=_join_schema(left.schema, rs_nullable),
            dicts=merged_dicts,
        )
        ovf = dict(ovf)
        ovf[nid] = jnp.maximum(total - cap, 0)
        return out, ovf

    # ---- aggregate emission --------------------------------------------
    def _emit_aggregate(self, op: Aggregate, nid, inputs, emit, params):
        child, ovf = emit(op.child, inputs)
        key_vals = []
        domains = []
        for _, e in op.group_keys:
            v, _ = evaluate(e, child)
            key_vals.append(v)
            domains.append(_dict_domain(child, e))

        # per-aggregate (op, values, effective row mask): count(col)/sum/min/
        # max skip NULL inputs via the argument's validity mask (SQL null
        # semantics; count(*) has arg None and counts all live rows)
        agg_ops, agg_vals, agg_masks = [], [], []
        for name, fn, arg, distinct in op.aggs:
            if distinct:
                raise NotImplementedError("DISTINCT aggregates")
            if arg is None:
                agg_ops.append("count")
                agg_vals.append(None)
                agg_masks.append(child.sel)
            else:
                v, vv = evaluate(arg, child)
                agg_ops.append(fn)
                agg_vals.append(None if fn == "count" else v)
                agg_masks.append(child.sel if vv is None else child.sel & vv)

        out_schema = _agg_schema(op, child.schema)

        if (
            op.group_keys
            and all(d is not None for d in domains)
            and int(np.prod([d for d in domains])) <= DIRECT_GROUPBY_MAX_DOMAIN
        ):
            packed, domain = pack_keys(key_vals, domains)
            live = jnp.zeros(domain, dtype=jnp.int64).at[
                jnp.where(child.sel, packed, domain)
            ].add(1, mode="drop")
            slot_used = live > 0
            # unpack keys from slot index
            bits = [max(1, int(d - 1).bit_length()) for d in domains]
            slots = jnp.arange(domain, dtype=jnp.int64)
            cols = {}
            shift = 0
            for (name, e), b in zip(op.group_keys, bits):
                t = infer_type(e, child.schema)
                cols[name] = ((slots >> shift) & ((1 << b) - 1)).astype(
                    t.storage_np
                )
                shift += b
            for (name, _, _, _), aop, av, am in zip(
                op.aggs, agg_ops, agg_vals, agg_masks
            ):
                cols[name] = _apply_agg(aop, packed, am, av, domain)
            sel = slot_used
        elif op.group_keys:
            ts = params.groupby_size[nid]
            row_slot, slot_used, slot_row = assign_group_slots(
                key_vals, child.sel, ts
            )
            pend = jnp.sum(child.sel & (row_slot < 0), dtype=jnp.int64)
            n = key_vals[0].shape[0]
            rep = jnp.clip(slot_row, 0, n - 1)
            cols = {}
            for (name, e), kv in zip(op.group_keys, key_vals):
                cols[name] = jnp.where(slot_used, kv[rep], 0)
            for (name, _, _, _), aop, av, am in zip(
                op.aggs, agg_ops, agg_vals, agg_masks
            ):
                cols[name] = _apply_agg(aop, row_slot, am, av, ts)
            sel = slot_used
            ovf = dict(ovf)
            ovf[nid] = pend
        else:
            # scalar aggregate: single-row output, per-agg masks; SQL
            # semantics: sum/min/max over ZERO rows is NULL (count is 0)
            from ..ops.hashagg import scalar_aggregate

            cols = {}
            out_valid = {}
            for (name, _, _, _), aop, av, am in zip(
                op.aggs, agg_ops, agg_vals, agg_masks
            ):
                (v,) = scalar_aggregate(am, [aop], [av])
                cols[name] = v[None]
                if aop != "count":
                    out_valid[name] = jnp.any(am)[None]
            sel = jnp.ones(1, dtype=jnp.bool_)

        dicts = {}
        for name, e in op.group_keys:
            if isinstance(e, E.ColRef) and e.name in child.dicts:
                dicts[name] = child.dicts[e.name]
        out = ColumnBatch(
            cols=cols,
            valid=(out_valid if not op.group_keys else {}),
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=out_schema,
            dicts=dicts,
        )
        return out, ovf

    # ---- execution ------------------------------------------------------
    def prepare(self, plan: LogicalOp) -> "PreparedPlan":
        """Compile once; the returned PreparedPlan caches the XLA executable
        (the expensive artifact — this is what the plan cache stores)."""
        params = self.seed_params(plan)
        jitted, input_spec, overflow_nodes = self.compile(plan, params)
        return PreparedPlan(self, plan, params, jitted, input_spec, overflow_nodes)

    def execute(self, plan: LogicalOp, max_retries: int = 3):
        return self.prepare(plan).run(max_retries)


class PreparedPlan:
    """A compiled plan: jitted XLA program + static capacities. Re-runnable;
    transparently recompiles at larger capacities on overflow."""

    def __init__(self, executor, plan, params, jitted, input_spec, overflow_nodes):
        self.executor = executor
        self.plan = plan
        self.params = params
        self.jitted = jitted
        self.input_spec = input_spec
        self.overflow_nodes = overflow_nodes
        self.retries = 0  # lifetime overflow-recompile count (plan monitor)

    def run(self, max_retries: int = 3, qparams: tuple = ()):
        for attempt in range(max_retries + 1):
            inputs = {
                alias: self.executor.table_batch(table, cols)
                for alias, table, cols in self.input_spec
            }
            out, ovf_vec = self.jitted(inputs, qparams)
            overflows = {
                nid: int(v)
                for nid, v in zip(self.overflow_nodes, ovf_vec)
                if int(v) > 0
            }
            if not overflows:
                return out
            if attempt == max_retries:
                raise RuntimeError(
                    f"capacity overflow after {max_retries} retries: {overflows}"
                )
            self.retries += 1
            self.params.bump(overflows)
            self.jitted, self.input_spec, self.overflow_nodes = (
                self.executor.compile(self.plan, self.params)
            )
        raise AssertionError


def _join_schema(ls: Schema, rs: Schema) -> Schema:
    return Schema(tuple(list(ls.fields) + list(rs.fields)))


def _agg_schema(op: Aggregate, child_schema: Schema) -> Schema:
    fields = []
    for name, e in op.group_keys:
        fields.append(Field(name, infer_type(e, child_schema)))
    for name, fn, arg, _ in op.aggs:
        if fn == "count":
            fields.append(Field(name, DataType.int64()))
        else:
            t = infer_type(arg, child_schema)
            if fn == "sum" and t.is_decimal:
                t = DataType.decimal(18, t.scale)
            elif fn == "sum" and t.is_integer:
                t = DataType.int64()
            fields.append(Field(name, t))
    return Schema(tuple(fields))
