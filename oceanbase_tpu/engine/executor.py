"""Physical codegen + execution: logical plan -> one jitted XLA program.

Reference surface: the code generator (ObStaticEngineCG,
sql/code_generator/ob_static_engine_cg.h:185) that lowers the logical plan
to an ObOpSpec tree, plus the ObOperator::get_next_batch driver loop
(sql/engine/ob_operator.cpp:1425). The TPU redesign collapses the operator
pull-loop entirely: the whole plan (or later, each DFO) traces into ONE XLA
computation over table ColumnBatches — scan masks, join gathers, group-by
scatters, sort permutations all fuse into a single device program, which is
the idiomatic TPU replacement for per-batch virtual dispatch.

Static-shape discipline (the ObBatchRows analog): every intermediate keeps
its producer's capacity with a live-row `sel` mask. Operators that change
cardinality (expand joins, group-bys) emit into planner-chosen static
capacities and return overflow counters; the host driver checks the
counters and re-executes with larger capacities (the TPU analog of the
reference's spill-to-disk: respill-to-a-larger-compile).

Physical choices made here (the optimizer's physical half):
- join: unique-build hash join when the build side's key covers a declared
  unique key of its base table; expand (sort+searchsorted) join otherwise.
- group-by: direct-addressed scatter when all keys are small-domain
  dictionary/bounded columns (packed perfect hash); open-addressing hash
  table otherwise (the reference's adaptive bypass, chosen statically).
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass, field, replace

import jax
import jax.numpy as jnp
import numpy as np

from ..core.column import ColumnBatch, batch_to_host
from ..core.dtypes import DataType, Field, Schema, TypeKind
from ..expr import ir as E
from ..expr.compile import (
    bind_value,
    compile_predicate,
    derive_dict_column,
    evaluate,
    infer_type,
)
from ..ops.hashagg import assign_group_slots, sort_groupby
from ..ops.hashing import next_pow2, pack_keys
from ..ops.join import (
    build_hash_table,
    expand_join,
    hash_join_probe,
    join_keys64,
    merge_join_unique,
    probe_run_any,
    sort_build_side,
)
from ..ops.sort import sort_indices
from ..sql.logical import (
    Aggregate,
    Distinct,
    Filter,
    JoinOp,
    Limit,
    LogicalOp,
    Project,
    Scan,
    SetOp,
    Sort,
    TopN,
    Window,
    output_schema,
    setop_schema,
    window_out_type,
)

# direct group-by = one fused masked reduction per (slot, aggregate): dirt
# cheap on the VPU for small domains (measured ~2.4ms for 8 slots over 8M
# rows) but linear in the domain, so the cap is small; larger domains ride
# the sort-based path (a TPU scatter costs ~1.1s per 8M rows, so the old
# scatter-direct design lost to sorting even at domain 8)
DIRECT_GROUPBY_MAX_DOMAIN = 1 << 6

# synthetic PhysicalParams id for the root result-compaction capacity
ROOT_COMPACT = -1

# synthetic overflow-node id space for the pack-validity guards (disjoint
# from plan node ids and the PX exchange-lane ids, parallel/px.py)
PACK_GUARD_BASE = 5_000_000

# synthetic overflow-node id space for ANN over-probe escalation: a
# candidate-starvation counter (live re-rank candidates < k) rides the
# overflow channel at ANN_PROBE_BASE + nid and bumps the node's
# effective nprobe instead of a capacity
ANN_PROBE_BASE = 9_000_000


def gather_payload(cols: dict, valid: dict, idx, sel=None):
    """Gather a whole batch payload by one index array via the packed
    row-gather (ops/gather.py). Use where len(idx) is comparable to the
    table length — the packing pass scans the full table once, so tiny
    index sets (top-n, root compaction) keep plain element gathers."""
    from ..ops.gather import gather_rows

    payload = {("c", n): c for n, c in cols.items()}
    payload.update({("v", n): v for n, v in valid.items()})
    if sel is not None:
        payload[("s", "")] = sel
    out = gather_rows(payload, idx)
    cols2 = {n: out[("c", n)] for n in cols}
    valid2 = {n: out[("v", n)] for n in valid}
    return cols2, valid2, out.get(("s", ""))


def compact_batch(b: ColumnBatch, cap2: int):
    """Compact live rows to a smaller capacity, preserving their relative
    order (stable sort by deadness). Returns (batch, overflow count).
    Used at plan roots so device->host result transfer moves O(result)
    bytes, not O(input capacity)."""
    if b.capacity <= cap2:
        return b, jnp.zeros((), jnp.int64)
    idx = jnp.arange(b.capacity, dtype=jnp.int32)
    _dead, sidx = jax.lax.sort((~b.sel, idx), num_keys=2)
    take = sidx[:cap2]
    nlive = jnp.sum(b.sel, dtype=jnp.int64)
    sel = jnp.arange(cap2, dtype=jnp.int64) < nlive
    out = ColumnBatch(
        cols={n: c[take] for n, c in b.cols.items()},
        valid={n: v[take] for n, v in b.valid.items()},
        sel=sel,
        nrows=jnp.minimum(nlive, cap2),
        schema=b.schema,
        dicts=b.dicts,
    )
    return out, jnp.maximum(nlive - cap2, 0)


@dataclass
class PhysicalParams:
    """Static capacities per plan node (keyed by pre-order node index;
    exchange lanes use synthesized ids, see parallel/px.py)."""

    groupby_size: dict[int, int] = field(default_factory=dict)
    join_cap: dict[int, int] = field(default_factory=dict)
    exchange_cap: dict[int, int] = field(default_factory=dict)
    # stats-packed group keys: nid -> ((vmin, bits) per key). A runtime
    # pack-validity counter rides the overflow channel (PACK_GUARD_BASE +
    # nid); overflow disables packing for that node and recompiles.
    pack_guard: dict[int, tuple] = field(default_factory=dict)
    groupby_nopack: set = field(default_factory=set)
    # clustered-FK segment aggregation specs (nid -> ClusteredAggSpec),
    # re-detected on every compile (deterministic from plan + catalog)
    clustered_aggs: dict = field(default_factory=dict)
    # range-pruned sorted-projection scans: nid -> _SliceSpec, with the
    # static slice capacity in scan_cap (overflow-bumped like join caps)
    scan_slice: dict = field(default_factory=dict)
    scan_cap: dict[int, int] = field(default_factory=dict)
    # top-k candidate prefilter capacities (TopN via lax.top_k on the
    # first key, exact under the tie-overflow guard)
    topn_cand: dict[int, int] = field(default_factory=dict)
    # ANN: TopN-over-vec_l2 nodes served by an IVF index (nid -> spec)
    vector_topns: dict = field(default_factory=dict)
    # ANN over-probe state: nid -> effective nprobe (survives the
    # per-compile vector_topns re-detection so an escalation sticks),
    # nid -> total list count (the escalation ceiling — probing every
    # list IS the exact answer, so the retry always resolves there)
    ann_nprobe: dict[int, int] = field(default_factory=dict)
    ann_lists: dict[int, int] = field(default_factory=dict)
    ann_escalations: int = 0  # lifetime over-probe bumps (sysstat delta)

    def bump(self, overflows: dict[int, int]):
        for nid in overflows:
            if nid >= ANN_PROBE_BASE:
                # candidate starvation (the filter decimated the probed
                # lists): escalate nprobe x8 toward the full list count —
                # recall-preserving over-probe, not post-filtering a
                # fixed-k result. x8 reaches any ceiling within the
                # standard retry budget (8 -> 64 -> 512 -> 4096).
                vid = nid - ANN_PROBE_BASE
                cur = self.ann_nprobe.get(vid)
                if cur is not None:
                    self.ann_nprobe[vid] = min(
                        cur * 8, self.ann_lists.get(vid, cur * 8))
                    self.ann_escalations += 1
                continue
            if nid >= PACK_GUARD_BASE:
                self.groupby_nopack.add(nid - PACK_GUARD_BASE)
                continue
            if nid in self.groupby_size:
                self.groupby_size[nid] *= 4
            if nid in self.join_cap:
                self.join_cap[nid] *= 4
            if nid in self.exchange_cap:
                self.exchange_cap[nid] *= 4
            if nid in self.scan_cap:
                # the slice capacity was seeded from ONE representative
                # parameter value; a wider runtime range is the normal
                # plan-cache reuse case, so the retry must always
                # resolve: drop back to the unsliced full scan (cap >=
                # table rows disables slicing in the Scan emission)
                self.scan_cap[nid] = 1 << 62
            if nid in self.topn_cand:
                # ties on a low-cardinality first key can exceed ANY
                # candidate budget: the retry must always resolve, so
                # one overflow disables the prefilter (cand >= capacity
                # skips it at emit) and the exact full sort runs
                self.topn_cand[nid] = 1 << 62


class ClusteredPremiseInvalidated(Exception):
    """A cached plan's clustered-FK premise no longer holds (the probe
    table's data changed and its fk column is no longer monotone);
    PreparedPlan.run recompiles, which re-detects and drops the spec."""


@dataclass(frozen=True)
class _SliceSpec:
    """Range bounds of a sorted-projection scan: the scan reads only the
    contiguous key range [max(lows), min(highs)) via device binary search
    + dynamic_slice (engine/executor.py Scan emission). Bounds are
    (Literal, searchsorted side) pairs so slotted literals keep the plan
    reusable across parameter values."""

    key: str                   # qualified sort-key column
    lows: tuple = ()           # (E.Literal, 'left'|'right') lower bounds
    highs: tuple = ()          # (E.Literal, 'left'|'right') upper bounds


@dataclass(frozen=True)
class VectorTopNSpec:
    """ORDER BY vec_l2(col, q) LIMIT k over an IVF-indexed scan: probe =
    centroid matmul + top-nprobe + contiguous-list candidate gather +
    exact re-rank matmul + top-k (storage/vector_index.py). Filter
    predicates between the TopN and the Scan ride INTO the fused kernel
    (evaluated as selection masks before the candidate re-rank) with
    recall preserved by over-probe: a starvation counter on the overflow
    channel escalates nprobe when the filter decimates the probed
    lists."""

    table: str
    column: str        # unqualified vector column
    qual_col: str      # alias-qualified name in the scan batch
    input_alias: str
    nprobe: int        # static: probed lists (over-probe escalated)
    max_list: int      # static: per-list read window
    nrows: int         # static: live rows of the table at compile
    k: int
    key: object        # the vec_l2 Func (resolved through the Project)
    scan: object       # the Scan node to emit
    proj: object       # Project between TopN and Scan (or None)
    filters: tuple = ()    # Filter predicates fused into the kernel
    lists: int = 0         # total IVF list count (escalation ceiling)
    base_nprobe: int = 0   # registered nprobe before over-probe seeding
    est_sel: float = 1.0   # estimated filter selectivity at compile
    ivf_cost: float = 0.0  # optimizer route cost, IVF side (EXPLAIN)
    brute_cost: float = 0.0  # route cost of the brute-force matmul
    cost_basis: str = "flops"  # "measured" when calibration records won


@dataclass(frozen=True)
class ClusteredAggSpec:
    """One Aggregate-over-PK-FK-join collapsed into segment reductions
    (see Executor._clustered_agg_spec)."""

    ji: object        # the JoinOp replaced by per-build-row range sums
    probe_table: str
    fk_col: str       # clustered probe key (unqualified storage column)
    fk_name: str      # qualified probe-side join key name
    build_table: str
    pk_col: str
    input_alias: str  # inputs key carrying the (starts, ends) arrays


def _number_nodes(plan: LogicalOp) -> dict[int, LogicalOp]:
    out = {}

    def rec(op):
        out[len(out)] = op
        for c in _children(op):
            rec(c)

    rec(plan)
    return out


def _children(op: LogicalOp):
    if isinstance(op, (Filter, Project, Sort, Limit, Distinct, Aggregate,
                       Window, TopN)):
        return [op.child]
    if isinstance(op, (JoinOp, SetOp)):
        return [op.left, op.right]
    return []


def _row_key_operands(cols, valid, schema):
    """Whole-row lexicographic sort operands with NULLs-compare-equal
    semantics: nullable columns contribute (zeroed values, validity flag)
    pairs; int64 columns split into two int32 planes (the multi-i64
    sort cliff, ops/sort.py). Returns (operands, spec) where spec records
    (name, nullable, dtype, nplanes) for _unpack_sorted. Shared by dedup
    and bag set-op kernels."""
    from ..ops.sort import split_sort_key

    operands: list[jnp.ndarray] = []
    spec: list[tuple[str, bool, object, int]] = []
    for f in schema.fields:
        c = cols[f.name]
        v = valid.get(f.name)
        cz = jnp.where(v, c, jnp.zeros((), c.dtype)) if v is not None else c
        planes = split_sort_key(cz)
        operands.extend(planes)
        if v is not None:
            operands.append(v)
        spec.append((f.name, v is not None, c.dtype, len(planes)))
    return operands, spec


def _run_boundaries(sorted_operands):
    """True at positions where any sorted operand differs from the previous
    row — the first row of each equal-value run."""
    n = sorted_operands[0].shape[0]
    new = jnp.zeros(n, jnp.bool_)
    for sv in sorted_operands:
        new = new | jnp.concatenate(
            [jnp.ones(1, jnp.bool_), sv[1:] != sv[:-1]]
        )
    return new


def _unpack_sorted(svals, spec):
    """Rebuild (cols, valid) dicts from sorted operands per the spec that
    _row_key_operands produced (int64 columns reassemble from planes)."""
    from ..ops.sort import rebuild_i64

    cols, valid = {}, {}
    i = 0
    for name, nullable, dtype, nplanes in spec:
        if nplanes == 2:
            cols[name] = rebuild_i64(svals[i], svals[i + 1])
        else:
            cols[name] = svals[i].astype(dtype)
        i += nplanes
        if nullable:
            valid[name] = svals[i]
            i += 1
    return cols, valid


def _dict_domain(batch: ColumnBatch, e: E.Expr) -> int | None:
    """Static domain size of a group key expr (dict columns, bools)."""
    if isinstance(e, E.ColRef):
        d = batch.dicts.get(e.name)
        if d is not None:
            return len(d)
        t = batch.schema[e.name]
        if t.kind is TypeKind.BOOL:
            return 2
        if t.kind is TypeKind.INT8:
            return 256
    return None


def _device_nbytes(obj) -> int:
    """Sum nbytes over the device arrays inside an executor input — a
    ColumnBatch, the PX raw cols/valid/sel dict, or derived-structure
    tuples (fk_ranges, ivf arrays)."""
    if hasattr(obj, "nbytes"):
        return int(obj.nbytes)
    if isinstance(obj, ColumnBatch):
        return (
            _device_nbytes(obj.cols)
            + _device_nbytes(obj.valid)
            + _device_nbytes(obj.sel)
        )
    if isinstance(obj, dict):
        return sum(_device_nbytes(v) for v in obj.values())
    if isinstance(obj, (tuple, list)):
        return sum(_device_nbytes(v) for v in obj)
    return 0


class Executor:
    # subclasses that manage their own placement (PX) disable chunking
    chunking_enabled = True
    # clustered-FK segment aggregation requires whole-table inputs in
    # storage order; sharded (PX) and chunk-streamed executors disable it
    clustered_agg_enabled = True
    # range-pruned slicing of sorted-projection scans needs whole-table
    # device columns (shards/chunks would misindex); the projection SWAP
    # itself is layout-only and stays on everywhere
    scan_slice_enabled = True

    def __init__(self, catalog, unique_keys=None, default_rows_estimate=1 << 16,
                 stats=None, device_budget=None, chunk_rows=None):
        self.catalog = catalog
        self.unique_keys = unique_keys or {}
        self.default_rows_estimate = default_rows_estimate
        # share/stats.StatsManager: NDV/histogram-backed cardinalities for
        # static capacities (None = heuristic constants)
        self.stats = stats
        # out-of-core: inputs beyond this many bytes stream through the
        # plan in chunks (engine/chunked.py); None = library default
        from .chunked import DEFAULT_CHUNK_ROWS, DEFAULT_DEVICE_BUDGET

        self.device_budget = (
            device_budget if device_budget is not None else DEFAULT_DEVICE_BUDGET
        )
        self.chunk_rows = chunk_rows or DEFAULT_CHUNK_ROWS
        self._batch_cache: dict[tuple[str, tuple], ColumnBatch] = {}
        # bumped by invalidate_table; derived device structures that span
        # TWO tables (fk_ranges) revalidate against both versions, since
        # the key-prefix delete in invalidate_table only covers one
        self._table_version: dict[str, int] = {}
        # lifetime host->device upload bytes (QueryProfile reads the delta
        # around one execution: cache hits upload nothing, which is the
        # point of the per-column device cache)
        self.h2d_bytes = 0
        # hook: share/timeline.ServingTimeline — cold uploads land as
        # transfer-interference events (the server wires it)
        self.timeline = None
        # assembled-ColumnBatch memo over the per-column cache: a warm
        # statement's _inputs() otherwise rebuilds the batch wrapper —
        # including a jnp.sum dispatch for nrows — on EVERY dispatch
        # (serving-path profile: ~80us/stmt). Validated by table version.
        self._assembled: dict[tuple, tuple[int, ColumnBatch]] = {}
        # cross-session micro-batching: lifetime count of batched-bucket
        # executables built (one per (plan, pow2 bucket) — the bench
        # asserts this stays bounded by the bucket count, not traffic)
        self.batched_compiles = 0
        # lifetime count of Executor.compile invocations (cold compiles +
        # overflow recompiles). Artifact-hydrated statements never come
        # through compile(), which is what the warm-boot smoke pins:
        # compiles + batched_compiles stays 0 across a warm replay
        self.compiles = 0
        # whole-statement fusion: lifetime count of narrowed (result-frame)
        # executables built — one per (plan, pow2 narrow bucket), same
        # bounding argument as batched_compiles
        self.narrow_compiles = 0
        # ANN observability: (table, col) -> last-build metadata (the
        # __all_virtual_vector_index rows' build side) and cumulative
        # per-index [queries, probes, escalations] counters folded by
        # the serving session per executed ANN statement
        self.ann_builds: dict = {}
        self.ann_stats: dict = {}
        # hook: engine/plan_profile.OperatorProfileStore — when wired
        # (server layer), measured TopN-route rates calibrate the
        # IVF-vs-brute cost comparison in _vector_topn_spec
        self.profile_store = None
        # hook: engine/memory_governor.MemoryGovernor — when wired, its
        # (OOM-shrunk) effective budget clamps the static device budget
        # so prepare() routes oversized inputs through the chunked path
        # instead of attempting an unguarded whole-table upload
        self.governor = None
        # set on degraded host-fallback executors: disables the
        # EN_DEVICE_OOM injection point (host execution cannot device-OOM)
        self.host_fallback = False
        # streaming pipeline knobs (engine/pipeline.py): prefetch depth 0
        # disables the prefetch thread (strictly alternating wire/compute
        # — the bench A/B baseline); stream_compress off ships raw
        # frame-of-reference chunks instead of the advisor encodings
        import os as _os

        self.stream_prefetch_depth = max(0, int(_os.environ.get(
            "OB_STREAM_PREFETCH",
            _os.environ.get("OB_STREAM_PIPELINE", "2"))))
        self.stream_compress = _os.environ.get(
            "OB_STREAM_COMPRESS", "1") not in ("0", "false", "off")

    # ---- input preparation -------------------------------------------
    def _collect_scans(self, plan: LogicalOp) -> list[Scan]:
        out = []

        def rec(op):
            if isinstance(op, Scan):
                out.append(op)
            for c in _children(op):
                rec(c)

        rec(plan)
        return out

    def _needed_columns(self, plan: LogicalOp) -> dict[str, set[str]]:
        """alias -> set of unqualified column names referenced anywhere."""
        needed: dict[str, set[str]] = {}

        def note(e: E.Expr):
            for q in E.referenced_columns(e):
                if "." in q:
                    a, c = q.split(".", 1)
                    needed.setdefault(a, set()).add(c)

        def rec(op):
            if isinstance(op, Scan) and op.pushed_filter is not None:
                note(op.pushed_filter)
            if isinstance(op, Filter):
                note(op.pred)
            if isinstance(op, Project):
                for _, e in op.exprs:
                    note(e)
            if isinstance(op, JoinOp):
                for e in op.left_keys + op.right_keys:
                    note(e)
                if op.residual is not None:
                    note(op.residual)
            if isinstance(op, Aggregate):
                for _, e in op.group_keys:
                    note(e)
                for _, _, a, _ in op.aggs:
                    if a is not None:
                        note(a)
            if isinstance(op, (Sort, TopN)):
                for e, _ in op.keys:
                    note(e)
            if isinstance(op, Window):
                for _name, fn, a, pk, ok, extra in op.funcs:
                    if a is not None:
                        note(a)
                    if fn in ("lag", "lead") and extra is not None \
                            and extra[1] is not None:
                        note(extra[1])
                    for p in pk:
                        note(p)
                    for oe, _d in ok:
                        note(oe)
            for c in _children(op):
                rec(c)

        rec(plan)
        return needed

    def _access_columns(self, plan: LogicalOp) -> dict[str, set]:
        """alias -> set of (column, role) pairs for the workload access
        stats: which columns the plan uses as filter predicates, join
        keys, group keys, or sort keys (server/workload.ROLE_* indices).
        Same reference walk as _needed_columns, keeping the role."""
        from ..server.workload import (
            ROLE_FILTER,
            ROLE_GROUP,
            ROLE_JOIN,
            ROLE_SORT,
        )

        acc: dict[str, set] = {}
        # output name -> defining expr across every Project in the plan:
        # the planner rewrites sort/group keys into synthetic projected
        # columns ($ordN), so an unqualified ColRef must chase its
        # definition back to the base columns it computes from
        defs: dict[str, E.Expr] = {}

        def collect_defs(op):
            if isinstance(op, Project):
                for name, e in op.exprs:
                    defs.setdefault(name, e)
            for c in _children(op):
                collect_defs(c)

        collect_defs(plan)

        def note(e: E.Expr, role: int, depth: int = 0):
            for q in E.referenced_columns(e):
                if "." in q:
                    a, c = q.split(".", 1)
                    acc.setdefault(a, set()).add((c, role))
                elif depth < 4 and q in defs:
                    note(defs[q], role, depth + 1)

        def rec(op):
            if isinstance(op, Scan) and op.pushed_filter is not None:
                note(op.pushed_filter, ROLE_FILTER)
            if isinstance(op, Filter):
                note(op.pred, ROLE_FILTER)
            if isinstance(op, JoinOp):
                for e in op.left_keys + op.right_keys:
                    note(e, ROLE_JOIN)
            if isinstance(op, Aggregate):
                for _, e in op.group_keys:
                    note(e, ROLE_GROUP)
            if isinstance(op, (Sort, TopN)):
                for e, _ in op.keys:
                    note(e, ROLE_SORT)
            for c in _children(op):
                rec(c)

        rec(plan)
        return acc

    def _access_profile(self, scans0: list, routed_plan: LogicalOp,
                        roles: dict[str, set]) -> tuple:
        """Static per-compiled-plan access profile: one entry per scan —
        (base table, row count at compile time, has sorted projections,
        routed to one, ((column, role), ...)). scans0 are the PRE-routing
        scans; routing is identity-preserving for plan structure, so the
        post-routing scan list pairs positionally (projection hits show
        as a changed scan.table). Virtual tables are excluded — querying
        the stats must not pollute them."""
        scans1 = self._collect_scans(routed_plan)
        out = []
        cat = self.catalog
        for s0, s1 in zip(scans0, scans1):
            if s0.table.startswith(("__all_virtual", "$")):
                # virtual tables and planner-internal relations (chunked
                # $partials overlays) are not workload objects
                continue
            t = cat[s0.table] if s0.table in cat else None
            rows = t.nrows if t is not None else 0
            has_proj = bool(getattr(t, "sorted_projections", None))
            cols = tuple(sorted(roles.get(s0.alias, ())))
            out.append((s0.table, rows, has_proj, s1.table != s0.table,
                        cols))
        return tuple(out)

    def invalidate_table(self, name: str) -> None:
        """Drop cached device batches of one table (its data changed)."""
        self._table_version[name] = self._table_version.get(name, 0) + 1
        for key in [k for k in self._batch_cache if k[0] == name]:
            del self._batch_cache[key]
        for key in [k for k in self._assembled if k[0] == name]:
            del self._assembled[key]

    def input_device_bytes(self, input_spec) -> int:
        """Device-resident footprint of a prepared plan's inputs (array
        nbytes at the operator boundary) — QueryProfile's device_bytes
        source. Called after execution, so every input is already in the
        device cache and this walks cached arrays without new uploads."""
        total = 0
        for alias, table, cols in input_spec:
            try:
                total += _device_nbytes(self.input_batch(alias, table, cols))
            except Exception:  # noqa: BLE001 - accounting must never fail a query
                continue
        return total

    def fk_ranges(self, probe_table: str, fk_col: str,
                  build_table: str, pk_col: str):
        """Device (starts, ends) int32 arrays over build-table rows: build
        row i joins exactly the probe rows [starts[i], ends[i]) — valid
        because the probe's fk column is stored CLUSTERED (monotone
        nondecreasing, checked by _monotone_col before any caller gets
        here). Host-precomputed by binary search once per table version and
        cached like device columns; this is the LSM analog of the
        reference's ordered-index row ranges (an FK sstable scan range per
        PK, cf. storage/access table scan ranges) and what lets a PK-FK
        join + group-by collapse into segment reductions with no sort and
        no per-probe-row gather."""
        vp = self._table_version.get(probe_table, 0)
        vb = self._table_version.get(build_table, 0)
        key = (probe_table, ("#fkr", fk_col, build_table, pk_col))
        hit = self._batch_cache.get(key)
        if hit is not None and hit[0] == (vp, vb):
            return hit[1]
        # data changed since the spec was detected: the clustering premise
        # must be re-proven, not assumed — a cached plan over a now
        # unsorted fk would binary-search garbage and silently mis-group
        if not self._monotone_col(probe_table, fk_col):
            raise ClusteredPremiseInvalidated(
                f"{probe_table}.{fk_col} is no longer monotone"
            )
        tp = self.catalog[probe_table]
        tb = self.catalog[build_table]
        fk = np.asarray(tp.data[fk_col])
        pk = np.asarray(tb.data[pk_col])
        lo = np.searchsorted(fk, pk, side="left").astype(np.int32)
        hi = np.searchsorted(fk, pk, side="right").astype(np.int32)
        cap = max(1024, -(-max(tb.nrows, 1) // 1024) * 1024)
        if cap > len(lo):
            pad = np.zeros(cap - len(lo), dtype=np.int32)
            lo = np.concatenate([lo, pad])
            hi = np.concatenate([hi, pad])
        dev = (jnp.asarray(lo), jnp.asarray(hi))
        self._batch_cache[key] = ((vp, vb), dev)
        return dev

    def input_batch(self, alias: str, table: str, cols: tuple):
        """One jit input from its input_spec entry: a table ColumnBatch,
        or a derived structure ('#fkr:' = clustered-FK join ranges,
        '#ivf:' = IVF vector-index arrays)."""
        if alias.startswith("#fkr:"):
            return self.fk_ranges(*cols)
        if alias.startswith("#ivf:"):
            tname, col, max_list = cols
            return self.ivf_device(tname, col, max_list)
        return self.table_batch(table, cols)

    def ivf_host(self, table: str, col: str):
        """Built IvfIndex for (table, col), staleness-checked two ways:
        the table VERSION (DML through invalidate_table bumps it) AND the
        column array's IDENTITY (weakref, same discipline as
        _monotone_col) — a memtable mutation that swapped t.data[col]
        without an invalidation hook must never serve a stale index
        silently. Invalidation = lazy rebuild on next use, same contract
        as sorted projections."""
        from ..storage.vector_index import build_ivf

        t = self.catalog[table]
        spec = getattr(t, "vector_indexes", {}).get(col)
        if spec is None:
            return None
        arr = t.data[col]
        v = self._table_version.get(table, 0)
        key = (table, ("#ivfh", col))
        hit = self._batch_cache.get(key)
        if hit is not None and hit[0] == v and hit[2]() is arr:
            return hit[1]
        t0 = time.perf_counter()
        idx = build_ivf(np.asarray(arr), lists=spec.lists)
        # weakref: a strong array ref would double-count host bytes in
        # the device census walk; the catalog holds the array anyway
        self._batch_cache[key] = (v, idx, weakref.ref(arr))
        self.ann_builds[(table, col)] = {
            "build_version": v,
            "build_unix": time.time(),
            "build_s": time.perf_counter() - t0,
            "rows": int(len(arr)),
        }
        return idx

    def ivf_device(self, table: str, col: str, expect_max_list: int):
        """(centroids, perm, offsets, lengths) device arrays; raises the
        premise-invalidated recompile signal when a rebuild changed the
        static window shape the compiled program assumed. Keyed on the
        host index OBJECT identity, not just the table version — an
        identity-detected rebuild (ivf_host's stale-array path) must
        re-upload even though the version never moved."""
        idx = self.ivf_host(table, col)
        if idx is None or idx.max_list != expect_max_list:
            raise ClusteredPremiseInvalidated(
                f"vector index on {table}.{col} changed shape"
            )
        v = self._table_version.get(table, 0)
        key = (table, ("#ivfd", col))
        hit = self._batch_cache.get(key)
        if hit is not None and hit[0] == v and hit[2] is idx:
            return hit[1]
        dev = (
            jnp.asarray(idx.centroids),
            jnp.asarray(idx.perm),
            jnp.asarray(idx.offsets),
            jnp.asarray(idx.lengths),
        )
        self._batch_cache[key] = (v, dev, idx)
        return dev

    def ann_residency(self) -> dict:
        """(table, column) -> device bytes of uploaded IVF artifacts.
        The governor charges these against tenant residency (an index the
        advisor keeps hot is memory the admission path must see), and
        __all_virtual_vector_index reads the same walk."""
        out: dict = {}
        for k, hit in list(self._batch_cache.items()):
            if (isinstance(k, tuple) and len(k) == 2
                    and isinstance(k[1], tuple) and k[1]
                    and k[1][0] == "#ivfd"):
                dev = hit[1]
                out[(k[0], k[1][1])] = sum(
                    int(getattr(a, "nbytes", 0)) for a in dev)
        return out

    def ann_device_bytes(self) -> int:
        return sum(self.ann_residency().values())

    # host-side monotonicity cache (id+weakref discipline: see
    # _affine_cache below for why a bare id is not enough)
    _monotone_cache: dict = {}

    def _monotone_col(self, table: str, col: str) -> bool:
        """True when the stored column array is monotone NONDECREASING —
        i.e. the table is physically clustered by this column (LSM tables
        laid out in key order; TPC-H lineitem by l_orderkey). Nullable
        columns are excluded: NULL rows carry arbitrary storage values."""
        try:
            t = self.catalog[table]
            arr = t.data[col]
        except (KeyError, AttributeError):
            return False
        if col in getattr(t, "valid", {}):
            return False
        if not isinstance(arr, np.ndarray) or arr.ndim != 1 or len(arr) < 1:
            return False
        if not np.issubdtype(arr.dtype, np.integer):
            return False
        key = id(arr)
        hit = Executor._monotone_cache.get(key)
        if hit is not None and hit[0]() is arr:
            return hit[1]
        if len(Executor._monotone_cache) > 4096:
            Executor._monotone_cache.clear()
        out = bool(np.all(arr[1:] >= arr[:-1]))
        Executor._monotone_cache[key] = (weakref.ref(arr), out)
        return out

    def table_batch(self, name: str, cols: tuple[str, ...]) -> ColumnBatch:
        if name == "$dual":  # FROM-less SELECT: one anonymous row
            return ColumnBatch(
                cols={"$one": jnp.zeros(1, jnp.int8)},
                valid={},
                sel=jnp.ones(1, jnp.bool_),
                nrows=jnp.ones((), jnp.int64),
                schema=Schema((Field("$one", DataType.int8()),)),
                dicts={},
            )
        is_private = getattr(self.catalog, "is_private", None)
        if is_private is not None and is_private(name):
            # tx-private view: never enters (or reads) the shared device
            # cache, so other sessions can't see uncommitted rows
            return self._build_batch(name, cols)
        # the device cache is PER COLUMN, not per column-set: queries with
        # overlapping needs share one H2D upload per column (uploads over
        # the network-attached chip cost ~seconds/GB and dominated the
        # bench when q1/q6/q3/q14 each re-shipped lineitem)
        ver = self._table_version.get(name, 0)
        memo = self._assembled.get((name, cols))
        if memo is not None and memo[0] == ver:
            return memo[1]
        t = self.catalog[name]
        sub_schema = Schema(
            tuple(f for f in t.schema.fields if f.name in cols)
        )
        n = t.nrows
        cap = max(1024, -(-max(n, 1) // 1024) * 1024)
        dcols: dict[str, jnp.ndarray] = {}
        dvalid: dict[str, jnp.ndarray] = {}
        for f in sub_schema.fields:
            key = (name, f.name)
            hit = self._batch_cache.get(key)
            if hit is None:
                from ..core.column import narrowed_upload

                a = np.asarray(t.data[f.name], dtype=f.dtype.storage_np)
                dev = narrowed_upload(a, cap)
                vdev = None
                if f.dtype.nullable:
                    v = (
                        np.asarray(t.valid[f.name], dtype=np.bool_)
                        if f.name in t.valid
                        else np.ones(n, dtype=np.bool_)
                    )
                    if cap > n:
                        v = np.concatenate(
                            [v, np.zeros(cap - n, dtype=np.bool_)])
                    vdev = jnp.asarray(v)
                hit = (dev, vdev)
                self._batch_cache[key] = hit
                nb = int(dev.nbytes) + (
                    int(vdev.nbytes) if vdev is not None else 0
                )
                self.h2d_bytes += nb
                tl = self.timeline
                if tl is not None and tl.enabled:
                    # a cold-column upload steals device time from the
                    # serving stream: transfer interference
                    tl.record_transfer(nb)
            dcols[f.name] = hit[0]
            if hit[1] is not None:
                dvalid[f.name] = hit[1]
        skey = (name, "#sel")
        sel = self._batch_cache.get(skey)
        if sel is None:
            s = np.zeros(cap, dtype=np.bool_)
            s[:n] = True
            sel = jnp.asarray(s)
            self._batch_cache[skey] = sel
            self.h2d_bytes += int(sel.nbytes)
            tl = self.timeline
            if tl is not None and tl.enabled:
                tl.record_transfer(int(sel.nbytes))
        batch = ColumnBatch(
            cols=dcols,
            valid=dvalid,
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=sub_schema,
            dicts={c: d for c, d in t.dicts.items() if c in cols},
        )
        self._assembled[(name, cols)] = (ver, batch)
        return batch

    def _build_batch(self, name: str, cols: tuple[str, ...]) -> ColumnBatch:
        t = self.catalog[name]
        sub_schema = Schema(
            tuple(f for f in t.schema.fields if f.name in cols)
        )
        from ..core.column import make_batch

        return make_batch(
            {c: t.data[c] for c in sub_schema.names()},
            sub_schema,
            {c: d for c, d in t.dicts.items() if c in cols},
            valid={c: v for c, v in t.valid.items() if c in cols},
        )

    # ---- physical parameter seeding ----------------------------------
    def _est_rows(self, op) -> float:
        """Cardinality estimate driving static capacities (and the PX
        layer's distribution-method choice)."""
        est_rows = self._est_rows
        if isinstance(op, Scan):
            if op.table == "$dual":
                return 1.0
            t = self.catalog[op.table]
            base = t.nrows or 1
            if op.pushed_filter is not None:
                ts = self.stats.table_stats(op.table) if self.stats else None
                if ts is not None and ts.nrows > 0:
                    base *= ts.selectivity(op.pushed_filter, t)
                else:
                    base *= 0.25 ** min(
                        len(self._conjuncts(op.pushed_filter)), 3
                    )
            return max(base, 1.0)
        if isinstance(op, Filter):
            return max(est_rows(op.child) * 0.5, 1.0)
        if isinstance(op, JoinOp):
            l = est_rows(op.left)
            r = est_rows(op.right)
            if op.kind in ("semi", "anti"):
                return max(l * 0.5, 1.0)
            if op.kind == "left":
                return l * 2
            if op.kind == "full":
                return l + r
            if not op.left_keys:  # cross / scalar broadcast
                return l if self._is_scalar_relation(op.right) else l * r
            if self._join_build_unique(op):
                # each probe row matches at most one build row; the MATCH
                # RATE is the filtered fraction of the build's key space
                # (containment): est(right)/|build base|. Floored at 0.05
                # — correlated filters make underestimates, and every
                # overflow retry is a recompile
                rb = self._build_base_rows(op.right)
                if rb and rb > 0:
                    return max(l * max(min(r / rb, 1.0), 0.05), 1.0)
                return l
            # M:N equi-join: |L||R| / max(ndv(Lkeys), ndv(Rkeys)) — the
            # textbook containment estimate (ob_opt_selectivity analog)
            lndv = self._keys_ndv(op.left, op.left_keys)
            rndv = self._keys_ndv(op.right, op.right_keys)
            if lndv is not None and rndv is not None:
                denom = max(min(lndv, l), min(rndv, r), 1.0)
                return max((l * r) / denom, 1.0)
            return max(l, r) * 2
        if isinstance(op, Aggregate):
            child = est_rows(op.child)
            nd = self._group_ndv(op)
            if nd is not None:
                return max(min(child, nd), 1.0)
            return min(child, float(self.default_rows_estimate))
        if isinstance(op, (Project, Sort, Distinct, Window)):
            return est_rows(op.child)
        if isinstance(op, (Limit, TopN)):
            return float(op.n + op.offset)
        if isinstance(op, SetOp):
            l, r = est_rows(op.left), est_rows(op.right)
            if op.kind == "union":
                return l + r
            if op.kind == "intersect":
                return min(l, r)
            return l  # except
        return float(self.default_rows_estimate)

    def _static_key_range(self, child: LogicalOp, e) -> tuple[int, int] | None:
        """(vmin, bits) for a group-key expr whose value domain is known
        statically: dictionary codes (exact domain from the dict length)
        or stats min/max (exact at collection; 4x headroom covers drift,
        and the runtime pack guard catches anything beyond). None = not
        packable."""
        name = e.name if isinstance(e, E.ColRef) else None
        if name is None:
            return None

        def resolve(node, name):
            if isinstance(node, Filter):
                return resolve(node.child, name)
            if isinstance(node, Project):
                nxt = dict(node.exprs).get(name)
                if not isinstance(nxt, E.ColRef):
                    return None
                return resolve(node.child, nxt.name)
            if isinstance(node, JoinOp):
                return resolve(node.left, name) or resolve(node.right, name)
            if isinstance(node, Scan) and "." in name:
                alias, col = name.split(".", 1)
                if alias == node.alias:
                    return (node.table, col)
            return None

        hit = resolve(child, name)
        if hit is None:
            return None
        table, col = hit
        try:
            t = self.catalog[table]
        except KeyError:
            return None
        d = t.dicts.get(col)
        if d is not None:
            dom = max(len(d), 1)
            # append-dictionaries can grow: headroom + runtime guard
            return 0, max((4 * dom - 1).bit_length(), 1)
        try:
            ct = t.schema[col]
        except Exception:
            return None
        if not np.issubdtype(ct.storage_np, np.integer):
            # float keys would TRUNCATE into the packed int domain and
            # merge distinct groups without tripping the range guard
            return None
        ts = self.stats.table_stats(table) if self.stats else None
        cs = ts.cols.get(col) if ts is not None else None
        if cs is None or cs.ndv <= 0:
            return None
        span = int(cs.vmax) - int(cs.vmin) + 1
        if span <= 0:
            return None
        return int(cs.vmin), max((4 * span - 1).bit_length(), 1)

    def seed_params(self, plan: LogicalOp) -> PhysicalParams:
        params = PhysicalParams()
        nodes = _number_nodes(plan)
        est_rows = self._est_rows

        # root compaction capacity: results travel device->host compacted
        # to the estimated output size (pulling a full input-capacity batch
        # to the host costs seconds at SF>=1); overflow retries apply
        params.join_cap[ROOT_COMPACT] = next_pow2(
            int(2 * est_rows(plan)) + 1024
        )
        # group-by / distinct / set-op dedup are sort-based: output reuses
        # the input capacity, so no table sizes (and no overflow retries)
        # are seeded for them
        for nid, op in nodes.items():
            if isinstance(op, Scan) and self.scan_slice_enabled:
                ps = getattr(self, "_pending_slices", {}).get(id(op))
                if ps is not None and nid not in params.scan_slice:
                    params.scan_slice[nid], params.scan_cap[nid] = ps
            if (
                isinstance(op, TopN)
                and self.clustered_agg_enabled  # whole-batch executors only
                and op.n + op.offset <= 1024
                and nid not in params.topn_cand
            ):
                params.topn_cand[nid] = max(
                    256, -(-4 * (op.n + op.offset) // 64) * 64
                )
            if (
                isinstance(op, Aggregate) and len(op.group_keys) > 1
                and op.grouping_sets is None
            ):
                # multi-key sort group-bys pack into ONE int64 sort key
                # when every key's domain is statically known: wide
                # multi-operand sorts go superlinear past ~16M rows on
                # v5e, a packed key keeps the canonical fast sort shape
                ranges = [
                    self._static_key_range(op.child, e)
                    for _n, e in op.group_keys
                ]
                if all(r is not None for r in ranges) and sum(
                    b for _v, b in ranges
                ) <= 62:
                    params.pack_guard[nid] = tuple(ranges)
            if isinstance(op, JoinOp):
                needs_cap = (
                    (op.kind in ("inner", "cross")
                     and not self._merge_joinable(op))
                    or (op.kind in ("semi", "anti") and op.residual is not None)
                    or op.kind in ("left", "full")
                )
                if needs_cap:
                    if op.kind in ("semi", "anti", "left", "full"):
                        # candidate-pair capacity, not output rows
                        cap = int(
                            max(est_rows(op.left), est_rows(op.right)) * 2
                        ) + 1024
                    else:
                        cap = int(est_rows(op)) * 2 + 1024
                    params.join_cap[nid] = -(-cap // 1024) * 1024
        return params

    # host-side column-layout property cache. Keyed by id(array) with a
    # WEAK reference in the value: a bare id can be reused by a new array
    # after the old one is GC'd (catalog refreshes replace DML tables'
    # arrays), which would silently apply a stale (a0, stride) to an
    # unrelated column and drop matching join rows. The weakref keeps the
    # check honest (dead ref or different object -> recompute) without
    # pinning superseded multi-MB columns until the 4096-entry clear.
    _affine_cache: dict[int, tuple["weakref.ref", tuple[int, int] | None]] = {}

    def _resolve_layout_col(self, node: LogicalOp, name: str):
        """(table, col) when output column `name` of `node` IS a base
        Scan's stored array (same length, same order — only the sel mask
        differs), seen through the layout-preserving ops: Filter, Project
        renames, and the PROBE side of joins that keep the probe layout
        (semi/anti always; inner via the merge/affine path, which emits
        probe columns untouched and only gathers build columns). None
        when the column is computed, gathered, or re-ordered."""
        while True:
            if isinstance(node, Filter):
                node = node.child
            elif isinstance(node, Project):
                nxt = dict(node.exprs).get(name)
                if not isinstance(nxt, E.ColRef):
                    return None
                name = nxt.name
                node = node.child
            elif isinstance(node, JoinOp) and (
                node.kind in ("semi", "anti")
                or (node.kind == "inner" and self._merge_joinable(node))
            ):
                # a build-side column would gather (new layout), but then
                # its alias only exists in the right subtree and the final
                # Scan-alias check below fails — the walk stays honest
                node = node.left
            else:
                break
        if not isinstance(node, Scan) or "." not in name:
            return None
        alias, col = name.split(".", 1)
        if alias != node.alias:
            return None
        return node.table, col

    def _affine_build_info(self, op: JoinOp) -> tuple[int, int] | None:
        """(a0, stride) when the build side's single join-key column is an
        AFFINE sequence in storage order (key[i] = a0 + stride*i) — true
        for identifier columns of LSM tables laid out in key order with
        regular keys (every TPC-H key column). Such joins skip sorting
        entirely: the matching build row is (key - a0) / stride, verified
        by one gather — a direct-address join (the TPU answer to the
        reference's hash table; cf. dense dict decoders in
        blocksstable/encoding). Filters/projections/layout-preserving
        joins above the scan keep the array layout (they only mask or
        rename), so the property holds through them."""
        if not op.left_keys or len(op.right_keys) != 1:
            return None
        e = op.right_keys[0]
        if not isinstance(e, E.ColRef):
            return None
        hit = self._resolve_layout_col(op.right, e.name)
        if hit is None:
            return None
        table, col = hit
        if "#sp:" in table:
            # routed projection scans may be DYNAMICALLY SLICED
            # (params.scan_slice): affine candidates index full-table
            # rows and would misindex the sliced batch
            return None
        try:
            arr = self.catalog[table].data[col]
        except (KeyError, AttributeError):
            return None
        if not isinstance(arr, np.ndarray) or arr.ndim != 1 or len(arr) < 2:
            return None
        key = id(arr)
        hit = Executor._affine_cache.get(key)
        if hit is not None and hit[0]() is arr:
            return hit[1]
        if len(Executor._affine_cache) > 4096:
            Executor._affine_cache.clear()
        out = None
        if np.issubdtype(arr.dtype, np.integer):
            stride = int(arr[1]) - int(arr[0])
            if stride > 0:
                d = np.diff(arr)
                if (d == stride).all():
                    out = (int(arr[0]), stride)
        Executor._affine_cache[key] = (weakref.ref(arr), out)
        return out

    def _merge_joinable(self, op: JoinOp) -> bool:
        """True when the join rides the combined-sort unique-build merge
        path (no pair expansion, no capacity): unique build side and one
        integer-typed key per side (dates, dict codes, ints, decimals —
        everything the engine stores as integers). Multi-column or
        non-integer keys go through expand_join, whose exact pair
        verification is collision-safe for hashed keys."""
        if not self._join_build_unique(op):
            return False
        if not op.left_keys:  # scalar-subquery cross: constant int key
            return True
        if len(op.left_keys) != 1:
            return False
        from ..expr.compile import infer_type

        try:
            lt = infer_type(op.left_keys[0], output_schema(op.left))
            rt = infer_type(op.right_keys[0], output_schema(op.right))
        except Exception:
            return False
        return (
            np.issubdtype(lt.storage_np, np.integer)
            and np.issubdtype(rt.storage_np, np.integer)
        )

    @staticmethod
    def _conjuncts(e):
        from ..sql.planner import split_conjuncts

        return split_conjuncts(e)

    def _keys_ndv(self, side: LogicalOp, keys) -> float | None:
        """Product of base-column NDVs for join keys resolvable to scans of
        `side` (None when any key isn't a plain column or stats are off)."""
        if self.stats is None:
            return None
        amap = {s.alias: s.table for s in self._collect_scans(side)}
        prod = 1.0
        for k in keys:
            if not isinstance(k, E.ColRef) or "." not in k.name:
                return None
            a, c = k.name.split(".", 1)
            tname = amap.get(a)
            if tname is None:
                return None
            ts = self.stats.table_stats(tname)
            nd = ts.ndv_of(c) if ts is not None else None
            if nd is None or nd <= 0:
                return None
            prod *= nd
        return prod

    def _build_base_rows(self, node: LogicalOp) -> float | None:
        """UNFILTERED row count of the base relation a unique-build side
        reads — the denominator of the join match-rate estimate. Walks
        the same layout chain as _join_build_unique."""
        while isinstance(node, (Filter, Project)):
            node = node.child
        if isinstance(node, JoinOp) and node.kind in ("inner", "semi", "anti"):
            return self._build_base_rows(node.left)
        if isinstance(node, Scan):
            try:
                return float(self.catalog[node.table].nrows or 1)
            except KeyError:
                return None
        return None

    def _group_ndv(self, op: Aggregate) -> float | None:
        """Product of group-key NDVs (grouping cardinality upper bound)."""
        if self.stats is None or not op.group_keys:
            return None
        prod = 1.0
        amap = {s.alias: s.table for s in self._collect_scans(op.child)}
        for _name, e in op.group_keys:
            if not isinstance(e, E.ColRef) or "." not in e.name:
                return None
            a, c = e.name.split(".", 1)
            tname = amap.get(a)
            if tname is None:
                return None
            ts = self.stats.table_stats(tname)
            nd = ts.ndv_of(c) if ts is not None else None
            if nd is None or nd <= 0:
                return None
            prod *= nd
        return prod

    @staticmethod
    def _is_scalar_relation(node: LogicalOp) -> bool:
        """True for a guaranteed-1-row relation (grand aggregate, possibly
        under projections/filters) — the broadcast side of a scalar-subquery
        join."""
        while isinstance(node, (Filter, Project)):
            node = node.child
        return isinstance(node, Aggregate) and not node.group_keys

    def _join_build_unique(self, op: JoinOp) -> bool:
        """True if the build (right) side's join keys cover a unique key of
        its source: a base table's declared unique key, an Aggregate's full
        group-key set, or a Distinct's full column set — seen through
        Filter/Project (renames followed) and through joins that cannot
        duplicate probe rows (semi/anti, and inner joins whose own build
        side is unique: each probe row matches at most one build row, so
        output rows are a subset of the probe side's rows and a unique key
        of the probe side stays unique)."""
        if self._is_scalar_relation(op.right):
            return True
        names = []
        for e in op.right_keys:
            if not isinstance(e, E.ColRef):
                return False
            names.append(e.name)
        node = op.right
        while True:
            if isinstance(node, Filter):
                node = node.child
            elif isinstance(node, Project):
                rename = {n: ex for n, ex in node.exprs}
                nxt = []
                for n in names:
                    ex = rename.get(n)
                    if not isinstance(ex, E.ColRef):
                        return False
                    nxt.append(ex.name)
                names = nxt
                node = node.child
            elif isinstance(node, JoinOp) and (
                node.kind in ("semi", "anti")
                or (node.kind == "inner" and self._join_build_unique(node))
            ):
                node = node.left
            else:
                break
        if isinstance(node, Aggregate):
            gk = {n for n, _ in node.group_keys}
            return bool(gk) and gk <= set(names)
        if isinstance(node, Distinct):
            cols = set(output_schema(node).names())
            return cols <= set(names)
        if isinstance(node, Scan):
            # a routed sorted projection keeps the base table's rows (and
            # so its unique keys) under the '#sp:' name
            base = node.table.split("#sp:", 1)[0]
            uks = tuple(self.unique_keys.get(node.table, ())) + tuple(
                self.unique_keys.get(base, ())
            )
            key_cols = {
                n.split(".", 1)[1] for n in names if n.startswith(node.alias + ".")
            }
            return any(set(uk) <= key_cols for uk in uks)
        return False

    # ---- sorted-projection scan routing -------------------------------
    _RANGE_KINDS = (TypeKind.DATE, TypeKind.INT8, TypeKind.INT16,
                    TypeKind.INT32, TypeKind.INT64)

    def _route_projections(self, plan: LogicalOp) -> LogicalOp:
        """Swap eligible Scans to sorted projections of their table (the
        index-selection step: a selective range predicate on a projection's
        sort key + covered columns). The swap alone is layout-only (same
        rows, different order) and correct under every executor; the
        contiguous-slice optimization rides separately via
        params.scan_slice where scan_slice_enabled."""
        self._pending_slices = {}
        needed = self._needed_columns(plan)

        def rec(op):
            # identity-preserving: PX keys distribution decisions by plan
            # node id, so untouched subtrees must come back AS-IS
            if isinstance(op, Scan):
                out = self._projection_choice(op, needed.get(op.alias, set()))
                return out if out is not None else op
            if isinstance(op, (JoinOp, SetOp)):
                left, right = rec(op.left), rec(op.right)
                if left is op.left and right is op.right:
                    return op
                return replace(op, left=left, right=right)
            if hasattr(op, "child"):
                child = rec(op.child)
                return op if child is op.child else replace(op, child=child)
            return op

        return rec(plan)

    def _projection_choice(self, scan: Scan, needed_cols: set):
        if scan.pushed_filter is None:
            return None
        try:
            t = self.catalog[scan.table]
        except KeyError:
            return None
        projs = getattr(t, "sorted_projections", None)
        if not projs:
            return None
        from ..expr.compile import bind_value

        conj = self._conjuncts(scan.pushed_filter)
        best = None
        for key_col, pname in projs.items():
            if key_col in t.dicts:
                continue  # dict codes are not value-ordered
            try:
                kt = t.schema[key_col]
            except Exception:
                continue
            if kt.kind not in self._RANGE_KINDS:
                continue  # decimal scales / floats: sides would mis-round
            qual = f"{scan.alias}.{key_col}"
            lows, highs = [], []
            for c in conj:
                for kind, lit in _range_bounds(c, qual):
                    if not (lit.value is not None
                            and lit.dtype.kind in self._RANGE_KINDS):
                        continue
                    if kind in ("ge", "gt"):
                        lows.append(
                            (lit, "left" if kind == "ge" else "right"))
                    elif kind in ("lt", "le"):
                        highs.append(
                            (lit, "left" if kind == "lt" else "right"))
                    else:  # eq
                        lows.append((lit, "left"))
                        highs.append((lit, "right"))
            if not lows and not highs:
                continue
            try:
                pt = self.catalog[pname]
            except KeyError:
                continue
            pcols = {f.name for f in pt.schema.fields}
            if not needed_cols <= pcols:
                continue
            arr = pt.data[key_col]
            n = len(arr)
            if n < 2:
                continue
            # representative bounds (parameterized literals keep their
            # planning-time value) -> exact count for the static capacity;
            # a different runtime value overflows and bumps the capacity
            lo_i = max(
                (int(np.searchsorted(arr, bind_value(l.value, l.dtype), s))
                 for l, s in lows), default=0,
            )
            hi_i = min(
                (int(np.searchsorted(arr, bind_value(h.value, h.dtype), s))
                 for h, s in highs), default=n,
            )
            cnt = max(hi_i - lo_i, 0)
            if cnt > 0.25 * n:
                continue  # not selective enough to beat the masked scan
            # tie-break equally selective candidates by covered width: a
            # narrower column-subset projection uploads fewer device
            # columns for the same slice
            width = len(pt.schema.fields)
            if best is None or (cnt, width) < (best[0], best[3]):
                best = (cnt, pname,
                        _SliceSpec(qual, tuple(lows), tuple(highs)), width)
        if best is None:
            return None
        cnt, pname, spec, _width = best
        new_scan = replace(scan, table=pname)
        cap = -(-int(cnt * 1.25 + 1024) // 1024) * 1024
        self._pending_slices[id(new_scan)] = (spec, cap)
        return new_scan

    # ---- ANN vector top-n ---------------------------------------------
    def _vector_topn_spec(self, op: TopN):
        """Match ORDER BY vec_l2(col, q) [ASC] LIMIT k over a Scan of a
        table with an IVF index on `col` — through an optional Project
        (hoisted $ordN) and any Filter chain / pushed scan filter — the
        ANN index route (the reference's vector-index DAS iterator,
        src/sql/das/iter). Index presence is the opt-in for approximate
        results, like obvec; whether the route actually wins is COSTED
        against the brute-force matmul (centroid pass + probed re-rank
        vs full-table distance), calibrated by measured TopN-route rates
        from the operator profile store when records exist. Filters ride
        into the fused kernel as selection masks; the filtered case
        seeds a recall-preserving over-probe from estimated selectivity
        and escalates at runtime via the overflow channel."""
        if op.offset != 0 or len(op.keys) != 1:
            return None
        e, desc = op.keys[0]
        if desc:
            return None
        node = op.child
        proj = None
        if isinstance(node, Project):
            # the planner hoists ORDER BY exprs into the projection as
            # $ordN; resolve the key ColRef back to its expression
            proj = node
            if isinstance(e, E.ColRef):
                e = dict(node.exprs).get(e.name, e)
            node = node.child
        if not isinstance(e, E.Func) or e.name != "vec_l2":
            return None
        filters = []
        filt_top = node
        while isinstance(node, Filter):
            filters.append(node.pred)
            node = node.child
        if not isinstance(node, Scan):
            return None
        colref = e.args[0]
        if not isinstance(colref, E.ColRef) or "." not in colref.name:
            return None
        alias, col = colref.name.split(".", 1)
        if alias != node.alias:
            return None
        try:
            t = self.catalog[node.table]
        except KeyError:
            return None
        spec = getattr(t, "vector_indexes", {}).get(col)
        if spec is None:
            return None
        idx = self.ivf_host(node.table, col)
        if idx is None or idx.max_list == 0:
            return None
        lists = len(idx.lengths)
        base_nprobe = max(1, min(spec.nprobe, lists))
        nprobe = base_nprobe
        filtered = bool(filters) or node.pushed_filter is not None
        est_sel = 1.0
        if filtered:
            # estimated survivor fraction under the predicate chain —
            # the over-probe seed: probing nprobe/est_sel lists keeps
            # the EXPECTED live candidate count at the unfiltered level
            # instead of post-filtering a decimated fixed-k result
            try:
                est_sel = float(self._est_rows(filt_top)) / max(
                    float(t.nrows), 1.0)
            except Exception:  # noqa: BLE001 - stats must not kill the route
                est_sel = 1.0
            est_sel = min(1.0, max(est_sel, 1e-6))
            boost = min(8, max(1, int(np.ceil(1.0 / max(est_sel, 0.125)))))
            nprobe = min(lists, nprobe * boost)
        # optimizer route: IVF work = centroid pass + probed-window
        # re-rank; brute work = full-table distance. Both are d-dim
        # matmul rows, so the un-calibrated comparison is row counts;
        # measured per-row rates from profiled TopN stages (PR 17
        # calibration records) replace the equal-rate assumption when
        # both routes have been observed
        d = int(np.asarray(idx.centroids).shape[1]) if lists else 1
        cand_rows = lists + nprobe * idx.max_list
        brute_rows = max(int(t.nrows), 1)
        ivf_cost = float(cand_rows * d)
        brute_cost = float(brute_rows * d)
        cost_basis = "flops"
        rates = None
        store = getattr(self, "profile_store", None)
        if store is not None:
            try:
                rates = store.ann_route_rates()
            except Exception:  # noqa: BLE001
                rates = None
        if rates is not None:
            ivf_cost = float(cand_rows) * rates[0]
            brute_cost = float(brute_rows) * rates[1]
            cost_basis = "measured"
        if ivf_cost >= brute_cost:
            # the index loses (tiny table, nprobe escalated to nearly
            # every list): brute-force exactly through the generic TopN
            return None
        return VectorTopNSpec(
            table=node.table,
            column=col,
            qual_col=colref.name,
            input_alias=f"#ivf:{node.table}.{col}",
            nprobe=nprobe,
            max_list=idx.max_list,
            nrows=t.nrows,
            k=op.n,
            key=e,
            scan=node,
            proj=proj,
            filters=tuple(filters),
            lists=lists,
            base_nprobe=base_nprobe,
            est_sel=est_sel,
            ivf_cost=ivf_cost,
            brute_cost=brute_cost,
            cost_basis=cost_basis,
        )

    def _emit_vector_topn(self, op: TopN, nid, spec: VectorTopNSpec,
                          inputs, emit, params):
        from ..expr.compile import evaluate_vector_literal

        # emit the SCAN, not the projection above it: the hoisted $ordN
        # distance column would otherwise evaluate over every row,
        # exactly the full matmul the index exists to avoid — the
        # projection re-applies over the k winners below
        child, ovf = emit(spec.scan, inputs)
        # fused filtered ANN: the Filter chain's predicates become
        # selection masks INSIDE this program (elementwise over the
        # batch — cheap next to the avoided full-table matmul); the
        # candidate re-rank below drops dead rows via child.sel
        for pred in spec.filters:
            child = child.with_sel(compile_predicate(pred, child))
        cent, perm, offs, lens = inputs[spec.input_alias]
        q = evaluate_vector_literal(spec.key.args[1])
        # round 1: nearest lists by centroid distance (rank-invariant
        # form drops ||q||^2 and ||x||^2-of-centroids keeps)
        cdist = jnp.sum(cent * cent, axis=1) - 2.0 * (cent @ q)
        _, probes = jax.lax.top_k(-cdist, spec.nprobe)
        starts = offs[probes]
        ll = lens[probes]
        win = starts[:, None] + jnp.arange(spec.max_list, dtype=jnp.int32)
        wvalid = (
            jnp.arange(spec.max_list, dtype=jnp.int32)[None, :] < ll[:, None]
        )
        n = spec.nrows
        rows = perm[jnp.clip(win, 0, max(n - 1, 0))].reshape(-1)
        wv = wvalid.reshape(-1)
        # round 2: exact re-rank of the candidate windows
        xv = child.cols[spec.qual_col][rows]          # (C, d) row gather
        dist = jnp.sum(xv * xv, axis=1) - 2.0 * (xv @ q)
        live = wv & child.sel[rows]
        dist = jnp.where(live, dist, jnp.inf)
        k = min(spec.k, rows.shape[0])
        if spec.nprobe < spec.lists:
            # over-probe escalation: when the fused filter decimates the
            # probed candidate windows below k live rows, report the
            # shortfall on the overflow channel; bump() widens nprobe and
            # the retry recompiles — recall-preserving, unlike
            # post-filtering a fixed-k result. Once nprobe == lists the
            # probe is exhaustive (exact), so no counter is emitted and
            # the retry ladder always terminates.
            ovf = dict(ovf)
            ovf[ANN_PROBE_BASE + nid] = jnp.maximum(
                jnp.int64(k) - jnp.sum(live, dtype=jnp.int64), jnp.int64(0))
        neg, top_i = jax.lax.top_k(-dist, k)
        win_rows = rows[top_i]
        cols, valid, _ = gather_payload(child.cols, child.valid, win_rows)
        sel = neg > -jnp.inf
        out = ColumnBatch(
            cols=cols,
            valid=valid,
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=child.schema,
            dicts=child.dicts,
        )
        if spec.proj is not None:
            out = self._project_batch(spec.proj, out)
        return out, ovf

    # ---- clustered-FK segment aggregation -----------------------------
    def _clustered_agg_spec(self, op: Aggregate):
        """Match Aggregate directly over an inner PK-FK join whose probe
        (left) side is a Filter chain over a Scan stored CLUSTERED by the
        single join key (monotone nondecreasing storage). The join +
        group-by then collapse into segment reductions: per-aggregate
        cumsums over the probe side in storage order, differenced at the
        host-precomputed per-build-row ranges (fk_ranges) — no sort, no
        hash table, no per-probe-row gather. The TPU redesign of the
        reference's group-by pushdown + vectorized hash join pair
        (rewrite/ob_transform_groupby_pushdown.cpp,
        engine/join/hash_join/ob_hash_join_vec_op.h:402): on a TPU the
        winning join is the one the storage layout already did.

        Matched shape:
        - group keys: exprs over the join key and/or build-side columns
          (each group IS one build row — build-side keys are functionally
          dependent on it because the build side is unique per key)
        - aggregates: non-DISTINCT sum/count over probe-side exprs
        - join: merge-joinable (unique build, single integer key both
          sides with equal storage types), no residual
        """
        if not op.group_keys or op.grouping_sets is not None:
            return None
        ji = op.child
        if (
            not isinstance(ji, JoinOp)
            or ji.kind != "inner"
            or ji.residual is not None
            or len(ji.left_keys) != 1
            or not isinstance(ji.left_keys[0], E.ColRef)
            or not isinstance(ji.right_keys[0], E.ColRef)
        ):
            return None
        if not self._merge_joinable(ji):
            return None
        try:
            lt = infer_type(ji.left_keys[0], output_schema(ji.left))
            rt = infer_type(ji.right_keys[0], output_schema(ji.right))
        except Exception:
            return None
        if lt.storage_np != rt.storage_np:
            # the group-key output substitutes the build pk for the probe
            # fk; a dtype mismatch would change the output column type
            return None
        node = ji.left
        while isinstance(node, Filter):
            node = node.child
        if not isinstance(node, Scan):
            return None
        base = node
        if "#sp:" in base.table:
            # routed sorted-projection scans may be DYNAMICALLY SLICED
            # (params.scan_slice): fk_ranges index full-table rows and
            # would misindex the sliced batch — never combine the two
            return None
        fk_name = ji.left_keys[0].name
        if "." not in fk_name:
            return None
        alias, fk_col = fk_name.split(".", 1)
        if alias != base.alias or not self._monotone_col(base.table, fk_col):
            return None
        hit = self._resolve_layout_col(ji.right, ji.right_keys[0].name)
        if hit is None:
            return None
        build_table, pk_col = hit
        if "#sp:" in build_table:
            return None  # same slicing hazard on the build side
        build_names = set(output_schema(ji.right).names())
        # groups must be 1:1 with build rows: some group key must BE the
        # join key itself (injective by identity). Keys that are merely
        # functions of the build side (group by customer attrs over an
        # orders build, TPC-H Q10) make groups COARSER than build rows
        # and need a second aggregation — generic path handles those.
        if not any(
            e == ji.left_keys[0] or e == ji.right_keys[0]
            for _n, e in op.group_keys
        ):
            return None
        for _name, e in op.group_keys:
            if not set(E.referenced_columns(e)) <= (build_names | {fk_name}):
                return None
        probe_names = set(output_schema(ji.left).names())
        for _name, fn, arg, distinct in op.aggs:
            if distinct or fn not in ("sum", "count"):
                return None
            if arg is not None and not (
                set(E.referenced_columns(arg)) <= probe_names
            ):
                return None
        input_alias = (
            f"#fkr:{base.table}.{fk_col}->{build_table}.{pk_col}"
        )
        return ClusteredAggSpec(
            ji, base.table, fk_col, fk_name, build_table, pk_col,
            input_alias,
        )

    def _emit_grouping_sets(self, op: Aggregate, nid, inputs, emit, params):
        """ROLLUP/CUBE/GROUPING SETS: aggregate once per set and stack
        the results, NULL-filling keys absent from a set — the engine's
        EXPAND (reference: the EXPAND phy operator replicates each input
        row per grouping set and NULL-masks; here the replication
        happens at the AGGREGATE level instead, which aggregates G
        smaller problems rather than one G-times-larger sort and lets
        each set reuse the engine's direct/packed/sort group-by routes).
        XLA CSE collapses the G re-traced child subtrees."""
        out_schema = _agg_schema(op, output_schema(op.child))
        parts = []
        ovf_all: dict = {}
        for si, idxs in enumerate(op.grouping_sets):
            sub = Aggregate(
                op.child,
                tuple(op.group_keys[i] for i in idxs),
                op.aggs,
            )
            # pseudo node id: nothing seeded, so sub-aggregates take the
            # parameter-free group-by routes (direct or unpacked sort)
            pseudo = -(1_000_000 + nid * 64 + si)
            b, ovf = self._emit_aggregate(sub, pseudo, inputs, emit, params)
            ovf_all.update(ovf)
            parts.append((idxs, b))
        cols: dict[str, list] = {n: [] for n in out_schema.names()}
        valid: dict[str, list] = {}
        sels = []
        key_names = [n for n, _e in op.group_keys]
        for idxs, b in parts:
            cap = b.capacity
            present = {key_names[i] for i in idxs}
            for f in out_schema.fields:
                n = f.name
                if n in present or n not in key_names:
                    cols[n].append(
                        b.cols[n].astype(f.dtype.storage_np))
                    v = b.valid.get(n)
                    if f.dtype.nullable:
                        valid.setdefault(n, []).append(
                            v if v is not None
                            else jnp.ones(cap, jnp.bool_)
                        )
                else:  # key absent from this set: NULL
                    cols[n].append(
                        jnp.zeros(cap, dtype=f.dtype.storage_np))
                    valid.setdefault(n, []).append(
                        jnp.zeros(cap, jnp.bool_))
            sels.append(b.sel)
        out = ColumnBatch(
            cols={n: jnp.concatenate(v) for n, v in cols.items()},
            valid={n: jnp.concatenate(v) for n, v in valid.items()},
            sel=jnp.concatenate(sels),
            nrows=sum(
                (jnp.sum(s, dtype=jnp.int64) for s in sels),
                jnp.zeros((), jnp.int64),
            ),
            schema=out_schema,
            dicts={
                n: d
                for _idxs, b in parts
                for n, d in b.dicts.items()
            },
        )
        return out, ovf_all

    def _emit_clustered_agg(self, op: Aggregate, nid, spec: ClusteredAggSpec,
                            inputs, emit, params):
        """Emit the matched Aggregate-over-join as segment reductions.

        Probe side (storage order, filters as sel): one cumsum per
        aggregate plus a live-row cumsum; build side: the group table —
        each live build row with >= 1 joined live probe row becomes a
        group, its aggregates the cumsum differences at [start, end).
        Exact (no hashing, no capacities, no overflow): the ranges are
        host-precomputed from the clustered key, and the count/sum
        semantics match the generic paths (NULL args skipped via
        validity; sum over an empty/all-NULL group yields 0 like
        sort_groupby's masked segmented cumsum)."""
        from ..ops.gather import gather_rows
        from ..sql.planner import _substitute

        ji = spec.ji
        L, lovf = emit(ji.left, inputs)
        R, rovf = emit(ji.right, inputs)
        ovf = {**lovf, **rovf}
        starts, ends = inputs[spec.input_alias]
        base_mask = L.sel
        running: dict = {"#cnt": jnp.cumsum(base_mask.astype(jnp.int64))}
        for i, (_name, fn, arg, _d) in enumerate(op.aggs):
            if arg is None:
                continue  # count(*) counts joined live rows == "#cnt"
            v, vv = evaluate(arg, L)
            am = base_mask if vv is None else base_mask & vv
            if fn == "count":
                running[i] = jnp.cumsum(am.astype(jnp.int64))
            else:
                acc = (
                    jnp.int64
                    if jnp.issubdtype(v.dtype, jnp.integer)
                    else v.dtype
                )
                running[i] = jnp.cumsum(jnp.where(am, v, 0).astype(acc))
        cap = L.capacity
        # ONE packed row-gather per bound materializes every aggregate's
        # running value (ops/gather.py); `upto(x) = c[x-1] if x>0 else 0`
        at_hi = gather_rows(running, jnp.clip(ends - 1, 0, cap - 1))
        at_lo = gather_rows(running, jnp.clip(starts - 1, 0, cap - 1))

        def seg(k):
            h = jnp.where(ends > 0, at_hi[k], 0)
            lo = jnp.where(starts > 0, at_lo[k], 0)
            return h - lo

        cnt = seg("#cnt")
        sel = R.sel & (cnt > 0)
        # group keys evaluate on the build side; the probe fk substitutes
        # to the build pk (equal on every surviving group by definition)
        sub = {ji.left_keys[0]: ji.right_keys[0]}
        cols, valid, dicts = {}, {}, {}
        for name, e in op.group_keys:
            e2 = _substitute(e, sub)
            v, vv = evaluate(e2, R)
            cols[name] = v
            if vv is not None:
                valid[name] = vv
            if isinstance(e2, E.ColRef) and e2.name in R.dicts:
                dicts[name] = R.dicts[e2.name]
        for i, (name, fn, arg, _d) in enumerate(op.aggs):
            cols[name] = cnt if arg is None else seg(i)
        out_schema = _agg_schema(op, output_schema(op.child))
        out = ColumnBatch(
            cols=cols,
            valid=valid,
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=out_schema,
            dicts=dicts,
        )
        # NOTE: compacting this output before the downstream TopN was
        # tried and measured SLOWER on chip (the sort-based compaction
        # costs a full extra build-capacity pass, more than the TopN
        # saves) — keep the full-capacity batch
        return out, ovf

    # ---- tracing ------------------------------------------------------
    def compile(self, plan: LogicalOp, params: PhysicalParams):
        self.compiles += 1
        nodes = _number_nodes(plan)
        id_of = {id(op): nid for nid, op in nodes.items()}
        needed = self._needed_columns(plan)
        # make sure every scan uploads at least one column (for row count)
        scans = self._collect_scans(plan)
        input_spec = []
        for s in scans:
            cols = needed.get(s.alias, set())
            if not cols:
                cols = (
                    {"$one"} if s.table == "$dual"
                    else {self.catalog[s.table].schema.fields[0].name}
                )
            input_spec.append((s.alias, s.table, tuple(sorted(cols))))

        # clustered-FK aggregates + ANN top-n: re-detect every compile
        # (deterministic from plan + catalog) and feed the precomputed
        # derived structures as inputs
        params.clustered_aggs.clear()
        params.vector_topns.clear()
        if self.clustered_agg_enabled:
            for nid2, op2 in nodes.items():
                if isinstance(op2, TopN):
                    vspec = self._vector_topn_spec(op2)
                    if vspec is not None:
                        # over-probe escalations survive re-detection: a
                        # prior bump() widened this node's nprobe and the
                        # recompile must honour it or the retry loops
                        esc = params.ann_nprobe.get(nid2)
                        if esc is not None and esc > vspec.nprobe:
                            vspec = replace(
                                vspec, nprobe=min(esc, vspec.lists))
                        params.ann_nprobe[nid2] = vspec.nprobe
                        params.ann_lists[nid2] = vspec.lists
                        params.vector_topns[nid2] = vspec
                        if all(a != vspec.input_alias
                               for a, _t, _c in input_spec):
                            input_spec.append((
                                vspec.input_alias,
                                vspec.table,
                                (vspec.table, vspec.column, vspec.max_list),
                            ))
                if not isinstance(op2, Aggregate):
                    continue
                spec = self._clustered_agg_spec(op2)
                if spec is not None:
                    params.clustered_aggs[nid2] = spec
                    if all(a != spec.input_alias for a, _t, _c in input_spec):
                        input_spec.append((
                            spec.input_alias,
                            spec.probe_table,
                            (spec.probe_table, spec.fk_col,
                             spec.build_table, spec.pk_col),
                        ))

        overflow_nodes: list[int] = sorted(
            set(params.groupby_size) | set(params.join_cap)
            | set(params.scan_cap) | set(params.topn_cand)
            | {
                PACK_GUARD_BASE + nid
                for nid in params.pack_guard
                if nid not in params.groupby_nopack
            }
            | {
                ANN_PROBE_BASE + nid
                for nid, vs in params.vector_topns.items()
                if vs.nprobe < vs.lists
            }
        )

        def emit(op, inputs) -> tuple[ColumnBatch, dict[int, jnp.ndarray]]:
            return self._emit_node(op, inputs, emit, params, id_of)

        qparam_spec = _collect_qparam_spec(plan)

        def run(inputs: dict[str, ColumnBatch], qparams: tuple = ()):
            from ..expr import compile as expr_compile

            qparams = _unpack_qparams(qparams, qparam_spec)
            prev = expr_compile.set_params(qparams if qparams else None)
            try:
                out, ovf = emit(plan, inputs)
            finally:
                expr_compile.set_params(prev)
            out, oc = compact_batch(out, params.join_cap[ROOT_COMPACT])
            ovf = dict(ovf)
            ovf[ROOT_COMPACT] = oc
            # ONE stacked vector: the host reads every counter in a single
            # fetch (per-scalar int() costs one tunnel roundtrip EACH)
            ovf_vec = jnp.stack([
                ovf.get(nid, jnp.zeros((), jnp.int64)) for nid in overflow_nodes
            ]) if overflow_nodes else jnp.zeros((0,), jnp.int64)
            return out, ovf_vec

        return jax.jit(run), input_spec, overflow_nodes

    def _emit_node(self, op, inputs, emit, params, id_of):
        """Emit one plan node into the traced program (dispatch shared by
        the single-chip and PX executors)."""
        nid = id_of[id(op)]
        if isinstance(op, Scan):
            b = inputs[op.alias]
            # qualify names
            qschema = Schema(
                tuple(
                    Field(f"{op.alias}.{f.name}", f.dtype)
                    for f in b.schema.fields
                )
            )
            qb = ColumnBatch(
                cols={f"{op.alias}.{n}": c for n, c in b.cols.items()},
                valid={f"{op.alias}.{n}": v for n, v in b.valid.items()},
                sel=b.sel,
                nrows=b.nrows,
                schema=qschema,
                dicts={f"{op.alias}.{n}": d for n, d in b.dicts.items()},
            )
            ovf = {}
            sl = params.scan_slice.get(nid)
            if sl is not None and sl.key in qb.cols:
                cap = params.scan_cap[nid]
                n = self.catalog[op.table].nrows
                if cap < n:
                    qb, over = _slice_sorted_scan(qb, sl, cap, n)
                    ovf[nid] = over
            if op.pushed_filter is not None:
                qb = qb.with_sel(compile_predicate(op.pushed_filter, qb))
            return qb, ovf

        if isinstance(op, Filter):
            child, ovf = emit(op.child, inputs)
            return child.with_sel(compile_predicate(op.pred, child)), ovf

        if isinstance(op, Project):
            child, ovf = emit(op.child, inputs)
            return self._project_batch(op, child), ovf

        if isinstance(op, JoinOp):
            return self._emit_join(op, nid, inputs, emit, params)

        if isinstance(op, Aggregate):
            return self._emit_aggregate(op, nid, inputs, emit, params)

        if isinstance(op, Distinct):
            child, ovf = emit(op.child, inputs)
            return self._dedup_batch(child, ovf)

        if isinstance(op, Sort):
            child, ovf = emit(op.child, inputs)
            keys, desc = [], []
            for e, d in op.keys:
                v, _ = evaluate(e, child)
                keys.append(v)
                desc.append(d)
            order = sort_indices(keys, desc, child.sel)
            cols, valid, ssel = gather_payload(
                child.cols, child.valid, order, child.sel
            )
            return (
                replace(child, cols=cols, valid=valid, sel=ssel),
                ovf,
            )

        if isinstance(op, Limit):
            child, ovf = emit(op.child, inputs)
            pos = jnp.cumsum(child.sel.astype(jnp.int64)) - 1
            keep = (
                child.sel
                & (pos >= op.offset)
                & (pos < op.offset + op.n)
            )
            return child.with_sel(keep), ovf

        if isinstance(op, TopN):
            vspec = params.vector_topns.get(nid)
            if vspec is not None and vspec.input_alias in inputs:
                return self._emit_vector_topn(
                    op, nid, vspec, inputs, emit, params
                )
            child, ovf = emit(op.child, inputs)
            cand = params.topn_cand.get(nid)
            if cand is not None and cand < child.capacity:
                got = self._topn_candidates(child, op.keys, cand)
                if got is not None:
                    mini, over = got
                    ovf = dict(ovf)
                    ovf[nid] = over
                    return (
                        self._topn_batch(mini, op.keys, op.n, op.offset),
                        ovf,
                    )
            return (
                self._topn_batch(child, op.keys, op.n, op.offset),
                ovf,
            )

        if isinstance(op, SetOp):
            return self._emit_setop(op, nid, inputs, emit, params)

        if isinstance(op, Window):
            return self._emit_window(op, nid, inputs, emit, params)

        raise NotImplementedError(type(op))

    def _project_batch(self, op: Project, child: ColumnBatch) -> ColumnBatch:
        cols, valid, dicts, fields = {}, {}, {}, []
        for name, e in op.exprs:
            derived = derive_dict_column(e, child)
            if derived is not None:
                # string transform (substr): new dict column
                v, vv, d2 = derived
                dicts[name] = d2
            else:
                v, vv = evaluate(e, child)
            if getattr(v, "ndim", 1) == 0:
                # all-literal expression: broadcast the scalar to the
                # batch (FROM-less SELECT constants)
                v = jnp.broadcast_to(v, (child.capacity,))
            cols[name] = v
            if vv is not None:
                valid[name] = vv
            t = infer_type(e, child.schema)
            fields.append(Field(name, t))
            if isinstance(e, E.ColRef) and e.name in child.dicts:
                dicts[name] = child.dicts[e.name]
        return ColumnBatch(
            cols=cols,
            valid=valid,
            sel=child.sel,
            nrows=child.nrows,
            schema=Schema(tuple(fields)),
            dicts=dicts,
        )

    def _topn_candidates(self, child: ColumnBatch, keys, C: int):
        """EXACT top-k candidate prefilter: lax.top_k on the FIRST sort
        key picks C candidates; any true top-(n+offset) row under the
        full lexicographic order has a first-key value >= the worst
        candidate's, so when at most C live rows tie-or-beat that value
        the candidate set is a superset — otherwise the tie count rides
        the overflow channel and the plan retries with 4x candidates.
        Replaces a full-capacity multi-operand sort (Q3: 15M rows) with
        one top_k + a C-row sort. None = ineligible (nullable,
        non-integer, or no key) and the generic sort path runs."""
        if not keys:
            return None
        e0, desc0 = keys[0]
        v, vv = evaluate(e0, child)
        if vv is not None or getattr(v, "ndim", 1) != 1:
            return None
        if not jnp.issubdtype(v.dtype, jnp.integer):
            return None  # float NaNs would outrank everything in top_k
        flip = v.astype(jnp.int64)
        if not desc0:
            flip = ~flip  # exact order reversal, no int64-min overflow
        dead = jnp.iinfo(jnp.int64).min
        masked = jnp.where(child.sel, flip, dead)
        cand_v, cand_i = jax.lax.top_k(masked, C)
        kth = cand_v[C - 1]
        cnt = jnp.sum((masked >= kth) & child.sel, dtype=jnp.int64)
        cols, valid, csel = gather_payload(
            child.cols, child.valid, cand_i, child.sel
        )
        # guard BOTH clip hazards: boundary ties beyond C, and a LIVE
        # row whose flipped key equals the dead sentinel being displaced
        # by dead rows inside top_k's index tie-break (it would vanish
        # with cnt <= C) — fewer live candidates than min(C, nlive)
        # means something real was dropped
        nlive = jnp.sum(child.sel, dtype=jnp.int64)
        live_cand = jnp.sum(csel, dtype=jnp.int64)
        short = jnp.maximum(
            jnp.minimum(jnp.int64(C), nlive) - live_cand, 0
        )
        over = jnp.maximum(cnt - C, 0) + short
        mini = ColumnBatch(
            cols=cols,
            valid=valid,
            sel=csel,
            nrows=jnp.sum(csel, dtype=jnp.int64),
            schema=child.schema,
            dicts=child.dicts,
        )
        return mini, over

    def _topn_batch(self, child: ColumnBatch, keys, n: int, offset: int,
                    apply_offset: bool = True) -> ColumnBatch:
        """Fused ORDER BY + LIMIT: sort for the order, materialize only the
        top n+offset rows (tiny gathers instead of a full-capacity payload
        permutation). The output keeps global order in its row order."""
        key_vals, desc = [], []
        for e, d in keys:
            v, _ = evaluate(e, child)
            key_vals.append(v)
            desc.append(d)
        order = sort_indices(key_vals, desc, child.sel)
        k = n + offset
        cap2 = min(child.capacity, max(8, -(-k // 8) * 8))
        take = order[:cap2]
        pos = jnp.arange(cap2, dtype=jnp.int64)
        nlive = jnp.sum(child.sel, dtype=jnp.int64)
        lo = offset if apply_offset else 0
        sel = (pos >= lo) & (pos < jnp.minimum(k, nlive))
        cols = {nm: c[take] for nm, c in child.cols.items()}
        valid = {nm: v[take] for nm, v in child.valid.items()}
        return ColumnBatch(
            cols=cols, valid=valid, sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=child.schema, dicts=child.dicts,
        )

    # ---- join emission -------------------------------------------------
    def _emit_join(self, op: JoinOp, nid, inputs, emit, params):
        if op.kind in ("semi", "anti"):
            return self._emit_semi_anti(op, nid, inputs, emit, params)
        if op.kind == "left":
            return self._emit_left(op, nid, inputs, emit, params)
        if op.kind == "full":
            return self._emit_full(op, nid, inputs, emit, params)
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ovf = {**lovf, **rovf}
        lkeys = [evaluate(e, left)[0] for e in op.left_keys]
        rkeys = [evaluate(e, right)[0] for e in op.right_keys]
        if not lkeys:
            # cross join: constant key makes every probe row match every
            # build row; a 1-row build (scalar subquery) rides the unique
            # hash path as a broadcast, general cross uses expand
            lkeys = [jnp.zeros(left.capacity, dtype=jnp.int32)]
            rkeys = [jnp.zeros(right.capacity, dtype=jnp.int32)]
        merged_dicts = {**left.dicts, **right.dicts}

        if self._merge_joinable(op):
            aff = self._affine_build_info(op) if op.left_keys else None
            cols = dict(left.cols)
            valid = dict(left.valid)
            if aff is not None:
                # direct address + ONE packed gather carrying the verify
                # key, build liveness, and every payload column together
                candc, in_range = _affine_candidates(
                    lkeys[0], aff, right.capacity)
                rcols, rvalid, rsel = gather_payload(
                    {**right.cols, "#bk": rkeys[0]},
                    right.valid, candc, right.sel,
                )
                bk_at = rcols.pop("#bk")
                sel = (
                    left.sel & in_range & (bk_at == lkeys[0]) & rsel
                )
            else:
                match = merge_join_unique(
                    rkeys[0], right.sel, lkeys[0], left.sel
                )
                sel = left.sel & (match >= 0)
                idx = jnp.clip(match, 0, None)
                rcols, rvalid, _ = gather_payload(
                    right.cols, right.valid, idx)
            cols.update(rcols)
            valid.update(rvalid)
            out_schema = _join_schema(left.schema, right.schema)
            out = ColumnBatch(
                cols=cols,
                valid=valid,
                sel=sel,
                nrows=jnp.sum(sel, dtype=jnp.int64),
                schema=out_schema,
                dicts=merged_dicts,
            )
        else:
            cap = params.join_cap[nid]
            skeys, order = sort_build_side(rkeys, right.sel)
            pr, br, valid_rows, total, _st, _of = expand_join(
                skeys, order, right.nrows, lkeys, left.sel, cap
            )
            cols, valid, _ = gather_payload(left.cols, left.valid, pr)
            rcols, rvalid, _ = gather_payload(right.cols, right.valid, br)
            cols.update(rcols)
            valid.update(rvalid)
            sel = valid_rows
            # multi-column keys ride a hash: exact-verify the expansion
            if len(op.left_keys) > 1:
                for le, re_ in zip(op.left_keys, op.right_keys):
                    lv, _ = evaluate(le, left)
                    rv, _ = evaluate(re_, right)
                    sel = sel & (lv[pr] == rv[br])
            out_schema = _join_schema(left.schema, right.schema)
            out = ColumnBatch(
                cols=cols,
                valid=valid,
                sel=sel,
                nrows=jnp.sum(sel, dtype=jnp.int64),
                schema=out_schema,
                dicts=merged_dicts,
            )
            ovf = dict(ovf)
            ovf[nid] = jnp.maximum(total - cap, 0)
        if op.residual is not None:
            out = out.with_sel(compile_predicate(op.residual, out))
        return out, ovf

    def _emit_semi_anti(self, op: JoinOp, nid, inputs, emit, params):
        """Semi/anti join: output = left rows with (without) a matching right
        row. No residual, single integer key: sorted-build + searchsorted
        range counts (exact — true keys, no hashing, no table). No residual,
        multi-column keys: the open-addressing existence probe (cold path).
        With residual: expand candidate pairs, evaluate the residual per
        pair, and reduce a has-match bit per left row scatter-free via the
        pair-run cumsum (probe_run_any)."""
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ovf = {**lovf, **rovf}
        lkeys = [evaluate(e, left)[0] for e in op.left_keys]
        rkeys = [evaluate(e, right)[0] for e in op.right_keys]
        if op.residual is None:
            if len(lkeys) == 1 and jnp.issubdtype(lkeys[0].dtype, jnp.integer) \
                    and jnp.issubdtype(rkeys[0].dtype, jnp.integer):
                aff = self._affine_build_info(op)
                if aff is not None:
                    has = _affine_probe(
                        rkeys[0], right.sel, lkeys[0], left.sel, aff
                    ) >= 0
                    sel = left.sel & (has if op.kind == "semi" else ~has)
                    return left.with_sel(sel), ovf
                skeys, _order = sort_build_side(rkeys, right.sel)
                pk = lkeys[0].astype(jnp.int64)
                lo = jnp.searchsorted(skeys, pk, side="left", method="sort")
                hi = jnp.searchsorted(skeys, pk, side="right", method="sort")
                # dead build rows sit at sorted positions >= right.nrows
                # with int64-max placeholders; clamp so a live probe key
                # of int64 max can't match them (dead probe rows are
                # masked by left.sel below)
                n_live = right.nrows.astype(lo.dtype)
                has = left.sel & (
                    jnp.minimum(hi, n_live) > jnp.minimum(lo, n_live)
                )
            else:
                nb = rkeys[0].shape[0]
                ts = next_pow2(max(2 * nb, 16))
                slot_key, slot_row = build_hash_table(rkeys, right.sel, ts)
                match = hash_join_probe(
                    slot_key, slot_row, rkeys, lkeys, left.sel
                )
                has = match >= 0
        else:
            cap = params.join_cap[nid]
            skeys, order = sort_build_side(rkeys, right.sel)
            pr, br, valid_rows, total, starts, offs = expand_join(
                skeys, order, right.nrows, lkeys, left.sel, cap
            )
            pair_sel = valid_rows
            if len(op.left_keys) > 1:
                for le, re_ in zip(op.left_keys, op.right_keys):
                    lv, _ = evaluate(le, left)
                    rv, _ = evaluate(re_, right)
                    pair_sel = pair_sel & (lv[pr] == rv[br])
            # pair batch: left cols gathered by pr, right cols by br
            pair_cols, pair_valid, _ = gather_payload(
                left.cols, left.valid, pr)
            _rc, _rv, _ = gather_payload(right.cols, right.valid, br)
            pair_cols.update(_rc)
            pair_valid.update(_rv)
            pair_batch = ColumnBatch(
                cols=pair_cols,
                valid=pair_valid,
                sel=pair_sel,
                nrows=jnp.sum(pair_sel, dtype=jnp.int64),
                schema=_join_schema(left.schema, right.schema),
                dicts={**left.dicts, **right.dicts},
            )
            pair_ok = compile_predicate(op.residual, pair_batch)
            has = probe_run_any(pair_ok, starts, offs)
            ovf = dict(ovf)
            ovf[nid] = jnp.maximum(total - cap, 0)
        sel = left.sel & (has if op.kind == "semi" else ~has)
        return left.with_sel(sel), ovf

    def _emit_left(self, op: JoinOp, nid, inputs, emit, params):
        """Left outer join via expansion: matched pairs plus, appended at a
        left-capacity tail, one all-NULL-right row for every unmatched left
        row. Right columns gain validity masks (they are nullable now)."""
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ovf = {**lovf, **rovf}
        lkeys = [evaluate(e, left)[0] for e in op.left_keys]
        rkeys = [evaluate(e, right)[0] for e in op.right_keys]
        cap = params.join_cap[nid]
        skeys, order = sort_build_side(rkeys, right.sel)
        pr, br, valid_rows, total, starts, offs = expand_join(
            skeys, order, right.nrows, lkeys, left.sel, cap
        )
        pair_sel = valid_rows
        if len(op.left_keys) > 1:
            for le, re_ in zip(op.left_keys, op.right_keys):
                lv, _ = evaluate(le, left)
                rv, _ = evaluate(re_, right)
                pair_sel = pair_sel & (lv[pr] == rv[br])
        merged_dicts = {**left.dicts, **right.dicts}
        if op.residual is not None:
            pair_cols, pair_valid, _ = gather_payload(
                left.cols, left.valid, pr)
            _rc, _rv, _ = gather_payload(right.cols, right.valid, br)
            pair_cols.update(_rc)
            pair_valid.update(_rv)
            pair_batch = ColumnBatch(
                cols=pair_cols,
                valid=pair_valid,
                sel=pair_sel,
                nrows=jnp.sum(pair_sel, dtype=jnp.int64),
                schema=_join_schema(left.schema, right.schema),
                dicts=merged_dicts,
            )
            pair_sel = compile_predicate(op.residual, pair_batch)
        nl = left.capacity
        has = probe_run_any(pair_sel, starts, offs)
        # output = [cap matched-pair slots] ++ [nl unmatched-left slots]
        lc_pr, lv_pr, _ = gather_payload(left.cols, left.valid, pr)
        rc_br, rv_br, _ = gather_payload(right.cols, right.valid, br)
        cols, valid = {}, {}
        for n, c in left.cols.items():
            cols[n] = jnp.concatenate([lc_pr[n], c])
        for n, v in left.valid.items():
            valid[n] = jnp.concatenate([lv_pr[n], v])
        for n, c in right.cols.items():
            cols[n] = jnp.concatenate(
                [rc_br[n], jnp.zeros_like(c, shape=(nl,))])
            matched_valid = (
                rv_br[n] if n in rv_br else jnp.ones(cap, jnp.bool_))
            valid[n] = jnp.concatenate([matched_valid, jnp.zeros(nl, jnp.bool_)])
        sel = jnp.concatenate([pair_sel, left.sel & ~has])
        rs_nullable = Schema(
            tuple(
                Field(f.name, f.dtype.with_nullable(True))
                for f in right.schema.fields
            )
        )
        out = ColumnBatch(
            cols=cols,
            valid=valid,
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=_join_schema(left.schema, rs_nullable),
            dicts=merged_dicts,
        )
        ovf = dict(ovf)
        ovf[nid] = jnp.maximum(total - cap, 0)
        return out, ovf

    # ---- set-operation emission ----------------------------------------
    @staticmethod
    def _cast_col(c, from_t: DataType, to_t: DataType):
        """Physically convert one column to the promoted set-op type."""
        if from_t.kind == to_t.kind and not to_t.is_decimal:
            return c.astype(to_t.storage_np) if c.dtype != to_t.storage_np else c
        if from_t.is_decimal and to_t.is_decimal:
            shift = 10 ** (to_t.scale - from_t.scale)
            return (c.astype(to_t.storage_np) * shift) if shift != 1 else c.astype(to_t.storage_np)
        if to_t.kind is TypeKind.FLOAT64:
            if from_t.is_decimal:
                return c.astype(jnp.float64) / from_t.decimal_factor
            return c.astype(jnp.float64)
        if to_t.is_integer:
            return c.astype(to_t.storage_np)
        raise NotImplementedError(f"set-op cast {from_t} -> {to_t}")

    @staticmethod
    def _setop_key_cols(cols, valids, schema: Schema):
        """Dedup/compare key columns with SQL set-op NULL semantics (NULLs
        compare equal): NULL payloads normalize to 0 and the validity bit
        joins the key."""
        keys = []
        for f in schema.fields:
            c = cols[f.name]
            v = valids.get(f.name)
            if v is not None:
                keys.append(jnp.where(v, c, jnp.zeros((), c.dtype)))
                keys.append(v)
            else:
                keys.append(c)
        return keys

    def _setop_promote(self, op: SetOp, left: ColumnBatch, right: ColumnBatch):
        """Positionally align both sides onto the common promoted schema:
        merged dictionaries, numeric casts, materialized validity. Returns
        (lb, rb, out_schema, dicts) — promoted same-schema batches. Split
        from the combine step so the PX layer can hash-exchange promoted
        rows (raw dict codes from different dictionaries would NOT
        co-partition equal strings)."""
        from ..core.dictionary import Dictionary

        out_schema = setop_schema(left.schema, right.schema)
        lcols, rcols, lvalid, rvalid, dicts = {}, {}, {}, {}, {}
        for i, f in enumerate(out_schema.fields):
            ln = left.schema.fields[i].name
            rn = right.schema.fields[i].name
            lt = left.schema.fields[i].dtype
            rt = right.schema.fields[i].dtype
            lc, rc = left.cols[ln], right.cols[rn]
            if f.dtype.kind is TypeKind.VARCHAR:
                md, lmap, rmap = Dictionary.merge(
                    left.dicts.get(ln), right.dicts.get(rn)
                )
                if md is not None:
                    dicts[f.name] = md
                if lmap is not None:
                    lc = jnp.asarray(lmap)[jnp.clip(lc, 0, len(lmap) - 1)]
                if rmap is not None:
                    rc = jnp.asarray(rmap)[jnp.clip(rc, 0, len(rmap) - 1)]
            else:
                lc = self._cast_col(lc, lt, f.dtype)
                rc = self._cast_col(rc, rt, f.dtype)
            lcols[f.name], rcols[f.name] = lc, rc
            if f.dtype.nullable:
                lv = left.valid.get(ln)
                rv = right.valid.get(rn)
                lvalid[f.name] = (
                    lv if lv is not None else jnp.ones(left.capacity, jnp.bool_)
                )
                rvalid[f.name] = (
                    rv if rv is not None else jnp.ones(right.capacity, jnp.bool_)
                )
        lb = ColumnBatch(
            cols=lcols, valid=lvalid, sel=left.sel, nrows=left.nrows,
            schema=out_schema, dicts=dicts,
        )
        rb = ColumnBatch(
            cols=rcols, valid=rvalid, sel=right.sel, nrows=right.nrows,
            schema=out_schema, dicts=dicts,
        )
        return lb, rb, out_schema, dicts

    def _emit_setop(self, op: SetOp, nid, inputs, emit, params):
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ovf = {**lovf, **rovf}
        lb, rb, out_schema, dicts = self._setop_promote(op, left, right)
        return self._setop_combine(op, lb, rb, out_schema, dicts, ovf)

    def _setop_combine(self, op: SetOp, left: ColumnBatch, right: ColumnBatch,
                       out_schema, dicts, ovf):
        """Combine two PROMOTED same-schema sides per the set-op kind."""
        lcols, rcols = left.cols, right.cols
        lvalid, rvalid = left.valid, right.valid

        if op.kind == "union":
            cols = {n: jnp.concatenate([lcols[n], rcols[n]]) for n in lcols}
            valid = {n: jnp.concatenate([lvalid[n], rvalid[n]]) for n in lvalid}
            sel = jnp.concatenate([left.sel, right.sel])
            out = ColumnBatch(
                cols=cols, valid=valid, sel=sel,
                nrows=jnp.sum(sel, dtype=jnp.int64),
                schema=out_schema, dicts=dicts,
            )
            if op.all:
                return out, ovf
            return self._dedup_batch(out, ovf)

        if op.all:
            # INTERSECT ALL / EXCEPT ALL (bag semantics): one combined
            # lexicographic sort of both sides with the side flag as the
            # LAST key, so within each equal-value run all left copies
            # precede the right copies. Per run with l left and r right
            # copies, the k-th left copy (k = 0..l-1) survives iff
            # k < r (INTERSECT ALL → min(l, r) copies) or k >= r
            # (EXCEPT ALL → max(l - r, 0) copies) — the run-length
            # counting form of ObHashSetVecOp's bag semantics
            # (sql/engine/set), recast as sort + prefix sums for the TPU.
            return self._emit_setop_all(
                op.kind, lcols, rcols, lvalid, rvalid,
                left, right, out_schema, dicts, ovf,
            )

        # INTERSECT / EXCEPT (distinct semantics): sort-dedup the left
        # side, then an existence probe against the right side decides each
        # surviving row
        lb = ColumnBatch(
            cols=lcols, valid=lvalid, sel=left.sel,
            nrows=left.nrows, schema=out_schema, dicts=dicts,
        )
        db, ovf = self._dedup_batch(lb, ovf)
        lkeys = self._setop_key_cols(db.cols, db.valid, out_schema)
        rkeys = self._setop_key_cols(rcols, rvalid, out_schema)
        # build table sized by right capacity: always large enough, so the
        # build needs no overflow accounting
        bts = next_pow2(max(2 * right.capacity, 16))
        slot_key, bslot_row = build_hash_table(rkeys, right.sel, bts)
        match = hash_join_probe(slot_key, bslot_row, rkeys, lkeys, db.sel)
        has = match >= 0
        sel = db.sel & (has if op.kind == "intersect" else ~has)
        return db.with_sel(sel), ovf

    def _emit_setop_all(self, kind, lcols, rcols, lvalid, rvalid,
                        left, right, out_schema, dicts, ovf):
        """INTERSECT ALL / EXCEPT ALL kernel (see caller comment)."""
        nl, nr = left.capacity, right.capacity
        n = nl + nr
        cols = {
            f.name: jnp.concatenate([lcols[f.name], rcols[f.name]])
            for f in out_schema.fields
        }
        valid = {
            name: jnp.concatenate([lvalid[name], rvalid[name]])
            for name in lvalid
        }
        live = jnp.concatenate([left.sel, right.sel])
        side = jnp.concatenate(
            [jnp.zeros(nl, jnp.int32), jnp.ones(nr, jnp.int32)]
        )
        operands, spec = _row_key_operands(cols, valid, out_schema)
        sorted_ = jax.lax.sort(
            (~live,) + tuple(operands) + (side,),
            num_keys=2 + len(operands),
        )
        sdead = sorted_[0]
        svals = sorted_[1:-1]
        sside = sorted_[-1]
        pos = jnp.arange(n, dtype=jnp.int64)
        # runs are delimited by value (and deadness) changes — NOT side
        new_run = _run_boundaries((sdead,) + tuple(svals))
        run_start = jax.lax.cummax(jnp.where(new_run, pos, 0))
        # exclusive run end = start of the NEXT run (suffix-min of marked
        # positions, shifted one left)
        marked = jnp.where(new_run, pos, n)
        suffix_min = jax.lax.cummin(marked[::-1])[::-1]
        run_end = jnp.concatenate(
            [suffix_min[1:], jnp.full(1, n, dtype=jnp.int64)]
        )
        is_left = sside == 0
        cum_left = jnp.cumsum(is_left.astype(jnp.int64))

        def left_before(x):
            return jnp.where(x > 0, cum_left[jnp.clip(x - 1, 0, n - 1)], 0)

        l_run = left_before(run_end) - left_before(run_start)
        r_run = (run_end - run_start) - l_run
        left_rank = pos - run_start
        keep = left_rank < r_run if kind == "intersect" \
            else left_rank >= r_run
        sel = ~sdead & is_left & keep
        out_cols, out_valid = _unpack_sorted(svals, spec)
        out = ColumnBatch(
            cols=out_cols, valid=out_valid, sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=out_schema, dicts=dicts,
        )
        return out, ovf

    def _dedup_batch(self, b: ColumnBatch, ovf):
        """Distinct over all columns with NULLs-compare-equal key semantics
        (shared by UNION and the Distinct operator). Sort-based: one
        multi-operand lexicographic sort, run boundaries mark the surviving
        representative rows — no hash table, no scatter, no capacity."""
        operands, spec = _row_key_operands(b.cols, b.valid, b.schema)
        sorted_ = jax.lax.sort(
            (~b.sel,) + tuple(operands), num_keys=1 + len(operands)
        )
        sdead = sorted_[0]
        svals = sorted_[1:]
        new = _run_boundaries((sdead,) + tuple(svals))
        sel = new & ~sdead
        cols, valid = _unpack_sorted(svals, spec)
        out = ColumnBatch(
            cols=cols, valid=valid, sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=b.schema, dicts=b.dicts,
        )
        return out, ovf

    # ---- window emission ------------------------------------------------
    def _emit_window(self, op: Window, nid, inputs, emit, params):
        from ..ops.window import (
            agg_identity,
            boundaries,
            peer_ends,
            segment_starts,
            segmented_scan_minmax,
            suffix_scan_minmax,
        )

        child, ovf = emit(op.child, inputs)
        n = child.capacity
        out_cols = dict(child.cols)
        out_valid = dict(child.valid)
        out_dicts = dict(child.dicts)
        fields = list(child.schema.fields)

        by_spec: dict[tuple, list] = {}
        for name, fn, arg, pk, ok, extra in op.funcs:
            by_spec.setdefault((pk, ok), []).append((name, fn, arg, extra))

        idx = jnp.arange(n, dtype=jnp.int64)
        for (pk, ok), funcs in by_spec.items():
            pkv = [evaluate(e, child)[0] for e in pk]
            okv, odesc = [], []
            for e, d in ok:
                v, _ = evaluate(e, child)
                okv.append(v)
                odesc.append(d)
            order = sort_indices(
                pkv + okv, [False] * len(pkv) + odesc, child.sel
            )
            ssel = child.sel[order]
            spk = [v[order] for v in pkv]
            sok = [v[order] for v in okv]
            if pk:
                new_seg = boundaries(spk)
            else:
                new_seg = jnp.zeros(n, jnp.bool_).at[0].set(True)
            # dead rows (capacity padding / filter-masked) sort to the
            # tail; the live->dead transition must start its OWN segment
            # or seg_end-based frames (ntile, lead defaults, UNBOUNDED
            # FOLLOWING) would count dead slots into the last partition
            new_seg = new_seg | jnp.concatenate(
                [jnp.ones(1, jnp.bool_), ssel[1:] != ssel[:-1]]
            )
            seg_start = segment_starts(new_seg)
            seg_end = peer_ends(new_seg)
            if ok:
                new_peer = new_seg | boundaries(sok)
                peer_start = segment_starts(new_peer)
                pend_idx = peer_ends(new_peer)
            else:
                # no ORDER BY: the frame is the whole partition — same code
                # as the running case with the peer group = the segment
                new_peer = peer_start = None
                pend_idx = seg_end
            # inverse permutation for the writeback: a sort, not a scatter
            # (a TPU scatter costs ~1.1s per 8M rows; argsort ~20ms)
            inv = jnp.argsort(order)

            def frame_lo_hi(extra):
                """Per-row inclusive frame bounds [lo, hi] in sorted space.
                None = the SQL default frame (partition start .. last peer
                with ORDER BY, whole partition without)."""
                if extra is None:
                    return seg_start, pend_idx
                unit, lo_b, hi_b = extra
                if unit == "rows":
                    lo = seg_start if lo_b is None else jnp.maximum(
                        seg_start, idx + lo_b)
                    hi = seg_end if hi_b is None else jnp.minimum(
                        seg_end, idx + hi_b)
                    return lo, hi
                # RANGE: value-based bounds on the single ASC-normalized
                # order key; CURRENT ROW maps to the peer group edges
                lo = hi = None
                if lo_b is None:
                    lo = seg_start
                elif lo_b == 0:
                    lo = peer_start
                if hi_b is None:
                    hi = seg_end
                elif hi_b == 0:
                    hi = pend_idx
                if lo is not None and hi is not None:
                    return lo, hi
                # numeric offset: binary search over a packed composite
                # (partition rank, key) that is globally nondecreasing —
                # the TPU replacement for the reference's per-row frame
                # cursor walk (ob_window_function_vec_op.cpp frames)
                kk = sok[0].astype(jnp.int64)
                kt = infer_type(ok[0][0], child.schema)
                if kt.is_decimal:
                    # RANGE offsets are in VALUE units; the key column
                    # stores scaled integers
                    lo_b = None if lo_b is None else lo_b * kt.decimal_factor
                    hi_b = None if hi_b is None else hi_b * kt.decimal_factor
                if odesc[0]:
                    # ~k = -k - 1: order-reversing like negation but with
                    # no int64-min overflow; the uniform -1 shift cancels
                    # in every key-vs-target comparison
                    kk = ~kk
                live_k = jnp.where(ssel, kk, 0)
                kmin = jnp.min(jnp.where(ssel, kk, jnp.iinfo(jnp.int64).max))
                kmax = jnp.max(jnp.where(ssel, kk, jnp.iinfo(jnp.int64).min))
                span = jnp.maximum(kmax - kmin + 1, 1)
                seg_rank = jnp.cumsum(new_seg.astype(jnp.int64)) - 1
                nseg_total = jnp.maximum(seg_rank[-1] + 1, 1)
                # (rank, key) packs into one int64 only while
                # nseg * span < 2^62; wide-domain keys fall back to an
                # exact per-segment binary search (33 gather rounds)
                # chosen at RUNTIME by lax.cond — wrong frames are not an
                # acceptable failure mode for silent wide domains
                pack_ok = span <= (1 << 62) // nseg_total
                span_c = jnp.minimum(span, (1 << 62) // nseg_total)
                packed = jnp.where(
                    ssel,
                    seg_rank * span_c + jnp.clip(live_k - kmin, 0, span_c),
                    jnp.iinfo(jnp.int64).max,
                )

                def _lex_bound(target, right):
                    """Insertion point of per-row `target` within the
                    row's own [seg_start, seg_end] run of the
                    segment-ascending key array — exact for any key
                    domain, ~log2(n) element-gather rounds."""
                    lo_ = seg_start.astype(jnp.int64)
                    hi_ = seg_end.astype(jnp.int64) + 1

                    def body(_i, lh):
                        l_, h_ = lh
                        mid = (l_ + h_) >> 1
                        kv = sok[0].astype(jnp.int64)[
                            jnp.clip(mid, 0, n - 1)
                        ]
                        if odesc[0]:
                            kv = ~kv
                        go = (kv <= target) if right else (kv < target)
                        act = l_ < h_
                        return (
                            jnp.where(act & go, mid + 1, l_),
                            jnp.where(act & ~go, mid, h_),
                        )

                    l_, _h = jax.lax.fori_loop(0, 34, body, (lo_, hi_))
                    return l_

                def _sat_add(v, off):
                    # saturating v + off: a wrapped target would flip the
                    # comparison direction; saturation costs at most the
                    # single boundary value int64 min/max
                    t = v + off
                    if off >= 0:
                        return jnp.where(
                            t < v, jnp.iinfo(jnp.int64).max, t)
                    return jnp.where(t > v, jnp.iinfo(jnp.int64).min, t)

                def bound_at(off, side):
                    # out-of-domain targets must yield EMPTY frames, not
                    # clamp onto the edge rows: a frame-start above the
                    # segment's keys resolves past its end (rel=span ->
                    # next segment's base -> lo > hi), a frame-end below
                    # resolves before its start (rel=-1 -> hi < lo)
                    off = max(min(off, (1 << 63) - 1), -(1 << 63))
                    if side == "lo":
                        def packed_fn(_):
                            rel = jnp.clip(
                                _sat_add(live_k - kmin, off), 0, span_c)
                            target = seg_rank * span_c + rel
                            return jnp.searchsorted(
                                packed, target, side="left", method="sort"
                            ).astype(jnp.int64)

                        return jax.lax.cond(
                            pack_ok, packed_fn,
                            lambda _: _lex_bound(_sat_add(live_k, off), False),
                            0,
                        )

                    def packed_fn(_):
                        rel = jnp.clip(
                            _sat_add(live_k - kmin, off), -1, span_c - 1)
                        target = seg_rank * span_c + rel
                        return jnp.searchsorted(
                            packed, target, side="right", method="sort"
                        ).astype(jnp.int64) - 1

                    return jax.lax.cond(
                        pack_ok, packed_fn,
                        lambda _: _lex_bound(_sat_add(live_k, off), True) - 1,
                        0,
                    )

                if lo is None:
                    lo = bound_at(lo_b, "lo")
                if hi is None:
                    hi = bound_at(hi_b, "hi")
                return lo, hi

            def csum_range(masked_vals, lo, hi):
                """Sum over [lo, hi] via one global inclusive cumsum
                (frames never cross segment bounds by construction)."""
                c = jnp.cumsum(masked_vals)
                hi_v = c[jnp.clip(hi, 0, n - 1)]
                lo_v = jnp.where(lo > 0, c[jnp.clip(lo - 1, 0, n - 1)], 0)
                return jnp.where(hi >= lo, hi_v - lo_v, 0)

            pending_cols: dict[str, jnp.ndarray] = {}
            pending_valid: dict[str, jnp.ndarray] = {}
            for name, fn, arg, extra in funcs:
                res_valid_sorted = None
                if fn == "row_number":
                    res_sorted = idx - seg_start + 1
                elif fn == "rank":
                    res_sorted = peer_start - seg_start + 1
                elif fn == "dense_rank":
                    dcum = jnp.cumsum(new_peer.astype(jnp.int64))
                    res_sorted = dcum - dcum[seg_start] + 1
                elif fn == "ntile":
                    k = jnp.int64(extra)
                    cnt = seg_end - seg_start + 1
                    j = idx - seg_start
                    q = cnt // k
                    r = cnt % k
                    cut = r * (q + 1)
                    res_sorted = jnp.where(
                        j < cut,
                        j // (q + 1),
                        r + (j - cut) // jnp.maximum(q, 1),
                    ) + 1
                elif fn in ("lag", "lead"):
                    off, dflt = extra
                    av, avv = evaluate(arg, child)
                    av_s = av[order]
                    srcvalid = ssel if avv is None else (ssel & avv[order])
                    src = idx - off if fn == "lag" else idx + off
                    inside = (
                        src >= seg_start if fn == "lag" else src <= seg_end
                    )
                    srcc = jnp.clip(src, 0, n - 1)
                    val = av_s[srcc]
                    vvalid = srcvalid[srcc]
                    if dflt is None:
                        res_sorted = jnp.where(inside, val, 0)
                        res_valid_sorted = inside & vvalid
                    else:
                        dv, dvv = evaluate(dflt, child)
                        dv_s = jnp.broadcast_to(dv, (n,))[order]
                        dvalid = (
                            jnp.ones(n, jnp.bool_)
                            if dvv is None else dvv[order]
                        )
                        res_sorted = jnp.where(
                            inside, val, dv_s.astype(val.dtype))
                        res_valid_sorted = jnp.where(
                            inside, vvalid, dvalid)
                elif fn in ("first_value", "last_value"):
                    av, avv = evaluate(arg, child)
                    av_s = av[order]
                    srcvalid = ssel if avv is None else (ssel & avv[order])
                    lo, hi = frame_lo_hi(extra)
                    at = lo if fn == "first_value" else hi
                    atc = jnp.clip(at, 0, n - 1)
                    res_sorted = av_s[atc]
                    res_valid_sorted = (hi >= lo) & srcvalid[atc]
                else:
                    # frame aggregate: count / sum via prefix-sum range
                    # reads; min/max via one-end-bounded segmented scans
                    if arg is None:
                        av_s, avv_s = None, None
                    else:
                        av, avv = evaluate(arg, child)
                        av_s = av[order]
                        avv_s = avv[order] if avv is not None else None
                    vmask = ssel if avv_s is None else (ssel & avv_s)
                    lo, hi = frame_lo_hi(extra)
                    frame_cnt = csum_range(vmask.astype(jnp.int64), lo, hi)
                    if fn == "count":
                        res_sorted = frame_cnt
                    elif fn == "sum":
                        acc = (
                            jnp.int64
                            if jnp.issubdtype(av_s.dtype, jnp.integer)
                            else av_s.dtype
                        )
                        mv = jnp.where(vmask, av_s.astype(acc), 0)
                        res_sorted = csum_range(mv, lo, hi)
                        res_valid_sorted = frame_cnt > 0
                    elif fn in ("min", "max"):
                        is_min = fn == "min"
                        ident = agg_identity(av_s.dtype, is_min)
                        mv = jnp.where(vmask, av_s, ident)
                        lo_unbounded = extra is None or extra[1] is None
                        if lo_unbounded:
                            res_sorted = segmented_scan_minmax(
                                mv, new_seg, is_min
                            )[jnp.clip(hi, 0, n - 1)]
                        else:
                            # hi unbounded (resolver guarantees one end)
                            res_sorted = suffix_scan_minmax(
                                mv, new_seg, is_min
                            )[jnp.clip(lo, 0, n - 1)]
                        res_valid_sorted = frame_cnt > 0
                    else:
                        raise NotImplementedError(f"window function {fn}")

                dt = window_out_type(fn, arg, child.schema)
                pending_cols[name] = res_sorted.astype(dt.storage_np)
                if res_valid_sorted is not None:
                    pending_valid[name] = res_valid_sorted
                    dt = dt.with_nullable(True)
                fields.append(Field(name, dt))
                if (
                    fn in ("min", "max", "lag", "lead",
                           "first_value", "last_value")
                    and isinstance(arg, E.ColRef)
                    and arg.name in child.dicts
                ):
                    out_dicts[name] = child.dicts[arg.name]

            # ONE packed writeback gather per window spec group (the
            # per-func res[inv] element gathers were the hot cost)
            wc, wv, _ = gather_payload(pending_cols, pending_valid, inv)
            out_cols.update(wc)
            out_valid.update(wv)

        out = ColumnBatch(
            cols=out_cols, valid=out_valid, sel=child.sel, nrows=child.nrows,
            schema=Schema(tuple(fields)), dicts=out_dicts,
        )
        return out, ovf

    def _emit_full(self, op: JoinOp, nid, inputs, emit, params):
        """Full outer join: matched pairs ++ unmatched-left tail (NULL
        right) ++ unmatched-right tail (NULL left). Both sides' columns
        gain validity masks. Cold path: the per-build-row matched bit uses
        one scatter (pairs are ordered by probe row, not build row)."""
        left, lovf = emit(op.left, inputs)
        right, rovf = emit(op.right, inputs)
        ovf = {**lovf, **rovf}
        lkeys = [evaluate(e, left)[0] for e in op.left_keys]
        rkeys = [evaluate(e, right)[0] for e in op.right_keys]
        cap = params.join_cap[nid]
        skeys, order = sort_build_side(rkeys, right.sel)
        pr, br, valid_rows, total, starts, offs = expand_join(
            skeys, order, right.nrows, lkeys, left.sel, cap
        )
        pair_sel = valid_rows
        if len(op.left_keys) > 1:
            for le, re_ in zip(op.left_keys, op.right_keys):
                lv, _ = evaluate(le, left)
                rv, _ = evaluate(re_, right)
                pair_sel = pair_sel & (lv[pr] == rv[br])
        merged_dicts = {**left.dicts, **right.dicts}
        if op.residual is not None:
            pair_cols, pair_valid, _ = gather_payload(
                left.cols, left.valid, pr)
            _rc, _rv, _ = gather_payload(right.cols, right.valid, br)
            pair_cols.update(_rc)
            pair_valid.update(_rv)
            pair_batch = ColumnBatch(
                cols=pair_cols, valid=pair_valid, sel=pair_sel,
                nrows=jnp.sum(pair_sel, dtype=jnp.int64),
                schema=_join_schema(left.schema, right.schema),
                dicts=merged_dicts,
            )
            pair_sel = compile_predicate(op.residual, pair_batch)
        nl, nr = left.capacity, right.capacity
        has_l = probe_run_any(pair_sel, starts, offs)
        has_r = (
            jnp.zeros(nr, dtype=jnp.bool_).at[br].max(pair_sel, mode="drop")
        )
        lc_pr, lv_pr, _ = gather_payload(left.cols, left.valid, pr)
        rc_br, rv_br, _ = gather_payload(right.cols, right.valid, br)
        cols, valid = {}, {}
        for n, c in left.cols.items():
            cols[n] = jnp.concatenate(
                [lc_pr[n], c, jnp.zeros_like(c, shape=(nr,))]
            )
            lv = left.valid.get(n)
            mv = lv_pr[n] if n in lv_pr else jnp.ones(cap, jnp.bool_)
            tv = lv if lv is not None else jnp.ones(nl, jnp.bool_)
            valid[n] = jnp.concatenate([mv, tv, jnp.zeros(nr, jnp.bool_)])
        for n, c in right.cols.items():
            cols[n] = jnp.concatenate(
                [rc_br[n], jnp.zeros_like(c, shape=(nl,)), c]
            )
            rv = right.valid.get(n)
            mv = rv_br[n] if n in rv_br else jnp.ones(cap, jnp.bool_)
            tv = rv if rv is not None else jnp.ones(nr, jnp.bool_)
            valid[n] = jnp.concatenate([mv, jnp.zeros(nl, jnp.bool_), tv])
        sel = jnp.concatenate(
            [pair_sel, left.sel & ~has_l, right.sel & ~has_r]
        )
        out_schema = output_schema(op)
        out = ColumnBatch(
            cols=cols, valid=valid, sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=out_schema, dicts=merged_dicts,
        )
        ovf = dict(ovf)
        ovf[nid] = jnp.maximum(total - cap, 0)
        return out, ovf

    # ---- aggregate emission --------------------------------------------
    def _emit_aggregate(self, op: Aggregate, nid, inputs, emit, params):
        if any(fn == "approx_ndv" for _n, fn, _a, _d in op.aggs) and (
            op.group_keys or op.grouping_sets is not None
        ):
            # grouped approx NDV: per-group register arrays would need a
            # [groups, 16K] sketch — the exact first-occurrence distinct
            # count is the better grouped plan (bounded by group rows)
            op = replace(op, aggs=tuple(
                (n, "count", a, True) if fn == "approx_ndv"
                else (n, fn, a, d)
                for n, fn, a, d in op.aggs
            ))
        if op.grouping_sets is not None:
            return self._emit_grouping_sets(op, nid, inputs, emit, params)
        spec = params.clustered_aggs.get(nid)
        if spec is not None and spec.input_alias in inputs:
            return self._emit_clustered_agg(
                op, nid, spec, inputs, emit, params
            )
        child, ovf = emit(op.child, inputs)
        key_vals = []
        key_valids = []
        domains = []
        for _, e in op.group_keys:
            v, vv = evaluate(e, child)
            if vv is None and isinstance(e, E.ColRef):
                vv = child.valid.get(e.name)
            if vv is not None:
                # SQL: NULLs form ONE group — canonicalize the value under
                # invalidity (it is arbitrary there) and key on (value,
                # validity) so NULL cannot merge with a genuine 0/""-coded
                # row (review: json_extract NULLs vs real empty strings)
                v = jnp.where(vv, v, jnp.zeros_like(v))
            key_vals.append(v)
            key_valids.append(vv)
            domains.append(_dict_domain(child, e))
        n_nullable = sum(1 for vv in key_valids if vv is not None)

        # per-aggregate (op, values, effective row mask): count(col)/sum/min/
        # max skip NULL inputs via the argument's validity mask (SQL null
        # semantics; count(*) has arg None and counts all live rows)
        agg_ops, agg_vals, agg_masks = [], [], []
        for name, fn, arg, distinct in op.aggs:
            if arg is None:
                agg_ops.append("count")
                agg_vals.append(None)
                agg_masks.append(child.sel)
            else:
                v, vv = evaluate(arg, child)
                am = child.sel if vv is None else child.sel & vv
                if distinct and fn in ("count", "sum", "avg"):
                    # DISTINCT: restrict the agg's mask to the first live
                    # occurrence of each (group keys, value); min/max are
                    # distinct-invariant and skip the extra sort. Validity
                    # planes join the dedup key — the NULL group must not
                    # share first-occurrences with the canonical-0 group
                    from ..ops.hashagg import distinct_first_mask

                    dk = key_vals + [
                        kv.astype(jnp.int32)
                        for kv in key_valids if kv is not None
                    ]
                    am = am & distinct_first_mask(dk, v, am)
                agg_ops.append(fn)
                agg_vals.append(None if fn == "count" else v)
                agg_masks.append(am)

        out_schema = _agg_schema(op, child.schema)

        out_valid = {}
        if (
            op.group_keys
            and all(d is not None for d in domains)
            and int(np.prod([d for d in domains])) * (2 ** n_nullable)
            <= DIRECT_GROUPBY_MAX_DOMAIN
        ):
            # direct path: one fused masked reduction per (slot, aggregate);
            # nullable keys contribute a domain-2 validity plane
            pk_vals, pk_doms = list(key_vals), list(domains)
            for vv in key_valids:
                if vv is not None:
                    pk_vals.append(vv.astype(jnp.int64))
                    pk_doms.append(2)
            packed, domain = pack_keys(pk_vals, pk_doms)
            slot_is = [packed == g for g in range(domain)]
            live = jnp.stack([
                jnp.sum(child.sel & g_, dtype=jnp.int64) for g_ in slot_is
            ])
            slot_used = live > 0
            # unpack keys from slot index
            bits = [max(1, int(d - 1).bit_length()) for d in pk_doms]
            slots = jnp.arange(domain, dtype=jnp.int64)
            cols = {}
            shift = 0
            for (name, e), b in zip(op.group_keys, bits):
                t = infer_type(e, child.schema)
                cols[name] = ((slots >> shift) & ((1 << b) - 1)).astype(
                    t.storage_np
                )
                shift += b
            for (name, _e), vv in zip(op.group_keys, key_valids):
                if vv is not None:
                    # each validity plane is exactly one bit, in key order
                    out_valid[name] = ((slots >> shift) & 1) == 1
                    shift += 1
            for (name, _, _, _), aop, av, am in zip(
                op.aggs, agg_ops, agg_vals, agg_masks
            ):
                cols[name] = _direct_slot_agg(aop, slot_is, am, av)
            sel = slot_used
        elif op.group_keys:
            # sort-based group-by: no hash table, no scatter, no capacity
            pack_spec = (
                params.pack_guard.get(nid)
                if nid not in params.groupby_nopack else None
            )
            if n_nullable:
                # validity planes don't fit the static pack spec: take the
                # multi-operand sort path (nullable keys are rare and never
                # the TPC-H hot group-bys)
                pack_spec = None
            if pack_spec is not None:
                # pack all keys into ONE int64 sort key (static bits from
                # stats/dict domains); a validity counter rides the
                # overflow channel — domain drift disables packing and
                # recompiles rather than mis-grouping
                pk = jnp.zeros(child.capacity, dtype=jnp.int64)
                invalid = jnp.zeros(child.capacity, dtype=jnp.bool_)
                for v, (vmin, bits) in zip(key_vals, pack_spec):
                    off = v.astype(jnp.int64) - vmin
                    invalid = invalid | (off < 0) | (off >= (1 << bits))
                    pk = (pk << bits) | jnp.clip(off, 0, (1 << bits) - 1)
                ovf = dict(ovf)
                ovf[PACK_GUARD_BASE + nid] = jnp.sum(
                    invalid & child.sel, dtype=jnp.int64
                )
                skeys_p, sel, agg_cols, order = sort_groupby(
                    [pk], child.sel, agg_ops, agg_vals, agg_masks
                )
                # decode the original key columns from the packed bits
                cols = {}
                shift = 0
                for (name, _e), v, (vmin, bits) in zip(
                    reversed(op.group_keys), reversed(key_vals),
                    reversed(pack_spec),
                ):
                    part = (skeys_p[0] >> shift) & ((1 << bits) - 1)
                    cols[name] = (part + vmin).astype(v.dtype)
                    shift += bits
            else:
                vplanes = [
                    vv.astype(jnp.int32) for vv in key_valids
                    if vv is not None
                ]
                skeys, sel, agg_cols, order = sort_groupby(
                    key_vals + vplanes, child.sel, agg_ops, agg_vals,
                    agg_masks
                )
                cols = {}
                for (name, _e), kv in zip(op.group_keys, skeys):
                    cols[name] = kv
                vi = len(op.group_keys)
                for (name, _e), vv in zip(op.group_keys, key_valids):
                    if vv is not None:
                        out_valid[name] = skeys[vi].astype(jnp.bool_)
                        vi += 1
            for (name, _, _, _), av in zip(op.aggs, agg_cols):
                cols[name] = av
        else:
            # scalar aggregate: single-row output, per-agg masks; SQL
            # semantics: sum/min/max over ZERO rows is NULL (count is 0)
            from ..ops.hashagg import scalar_aggregate

            cols = {}
            for (name, _, _, _), aop, av, am in zip(
                op.aggs, agg_ops, agg_vals, agg_masks
            ):
                (v,) = scalar_aggregate(am, [aop], [av])
                cols[name] = v[None]
                if aop not in ("count", "approx_ndv"):
                    out_valid[name] = jnp.any(am)[None]
            sel = jnp.ones(1, dtype=jnp.bool_)

        dicts = {}
        for name, e in op.group_keys:
            if isinstance(e, E.ColRef) and e.name in child.dicts:
                dicts[name] = child.dicts[e.name]
        out = ColumnBatch(
            cols=cols,
            valid=out_valid,
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=out_schema,
            dicts=dicts,
        )
        return out, ovf

    # ---- execution ------------------------------------------------------
    def make_chunk_source(self, stream_table: str, chunk_rows: int):
        """Chunk-program executor for out-of-core streaming (overridden by
        the PX layer so each chunk dispatches as one shard_map program)."""
        from .chunked import _ChunkSourceExecutor

        return _ChunkSourceExecutor(
            self.catalog, stream_table, chunk_rows,
            unique_keys=self.unique_keys, stats=self.stats,
        )

    def _clamped_chunk_rows(self, plan, stream, budget: int) -> int:
        """Chunk rows sized from the DECODED on-device width of the
        streamed columns: the pipeline holds up to depth+1 decoded chunks
        in flight, so each must fit its slice of the budget. The staged
        (compressed) host bytes are charged separately through the
        governor's staged ledger and do not enter this sizing — sizing
        from wire bytes would let a high-ratio RLE column overcommit HBM
        by its encoding ratio."""
        from .memory_governor import derive_chunk_rows
        from .pipeline import decoded_row_bytes

        needed = self._needed_columns(plan).get(stream.alias) or set()
        row_b = decoded_row_bytes(
            self.catalog, stream.table, sorted(needed))
        slots = max(1, int(getattr(self, "stream_prefetch_depth", 2))) + 1
        return derive_chunk_rows(
            max(1, budget // slots), self.chunk_rows, row_bytes=row_b)

    def prepare(self, plan: LogicalOp):
        """Compile once; the returned PreparedPlan caches the XLA executable
        (the expensive artifact — this is what the plan cache stores).
        Inputs beyond the device budget return a ChunkedPreparedPlan that
        streams the biggest table through the program (engine/chunked.py)."""
        scans0 = self._collect_scans(plan)
        roles = self._access_columns(plan)
        plan = self._route_projections(plan)
        # workload access heat: computed ONCE at compile time, folded per
        # execution from the prepared plan (no plan walks on the hot path)
        access = self._access_profile(scans0, plan, roles)
        if self.chunking_enabled:
            from .chunked import (
                ChunkedPreparedPlan,
                NotStreamable,
                _find_stream_split,
                plan_input_bytes,
            )

            # the memory governor's effective budget (shrunk after any
            # observed OOM) clamps the static streaming threshold, so an
            # oversized scan is routed through the chunked path up front
            # instead of gambling on a whole-table upload
            budget = self.device_budget
            gov = self.governor
            if gov is not None:
                budget = min(budget, gov.upload_budget())
            # mesh executors shard every upload over N devices, so the
            # per-device budget admits N x the single-chip working set
            # before degrading to chunk streaming (PxExecutor sets
            # budget_scale = mesh size; single-chip has no attribute)
            budget *= max(1, int(getattr(self, "budget_scale", 1)))
            if plan_input_bytes(self, plan) > budget:
                try:
                    stream, split, kind = _find_stream_split(
                        self, plan, budget)
                    chunk_rows = self._clamped_chunk_rows(
                        plan, stream, budget)
                    cp = ChunkedPreparedPlan(
                        self, plan, stream, split, kind, chunk_rows
                    )
                    cp.access_profile = access
                    return cp
                except NotStreamable:
                    # grace-hash partitioned spill: when even the BUILD
                    # side exceeds the budget, partition both sides to
                    # host segments and stream partition pairs through
                    # one static program (engine/pipeline.py). Mesh
                    # executors shard instead (budget_scale > 1).
                    if int(getattr(self, "budget_scale", 1)) == 1:
                        from .pipeline import NotPartitionable, try_grace_hash

                        try:
                            gp = try_grace_hash(self, plan, budget)
                            gp.access_profile = access
                            return gp
                        except NotPartitionable:
                            pass
                    # whole-table upload: governor-accounted at admission;
                    # a residual device OOM is absorbed by the retry
                    # ladder (evict -> chunk -> host), never a crash
                    pass
        params = self.seed_params(plan)
        jitted, input_spec, overflow_nodes = self.compile(plan, params)
        prepared = PreparedPlan(
            self, plan, params, jitted, input_spec, overflow_nodes)
        prepared.access_profile = access
        # optimizer estimates pinned at compile time: the calibration
        # half of every (estimate, actual) pair the operator profiler
        # records (engine/plan_profile.py)
        from ..sql.planner import capture_node_estimates

        prepared.node_estimates = capture_node_estimates(self, plan)
        return prepared

    def execute(self, plan: LogicalOp, max_retries: int = 3):
        return self.prepare(plan).run(max_retries)


def _collect_qparam_spec(plan) -> list | None:
    """Parameter slots of a parameterized plan, in slot order: list of
    (DataType, offset, width) per slot, or None when any parameter cannot
    ride the packed int64 vector. Scalars take one int64 lane; VECTOR
    slots take `precision` lanes (each float32 component widened to
    float64 bits) so a query embedding is ONE bound parameter block and
    ANN statements batch like point reads. The packed form exists because
    every separate qparam scalar is one more host->device transfer per
    dispatch — through the axon tunnel each costs a roundtrip."""
    import dataclasses as _dc

    slots: dict[int, object] = {}
    bad = False

    def expr_walk(e):
        nonlocal bad
        if isinstance(e, E.Literal):
            if e.slot is not None:
                if (e.dtype.kind is TypeKind.VECTOR
                        and int(e.dtype.precision or 0) <= 0):
                    bad = True  # unknown dimension: cannot size the block
                slots[e.slot] = e.dtype
            return
        if not hasattr(e, "__dataclass_fields__"):
            return
        for f in _dc.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, E.Expr):
                expr_walk(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, E.Expr):
                        expr_walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, E.Expr):
                                expr_walk(y)

    def op_walk(op):
        for f in _dc.fields(op):
            v = getattr(op, f.name)
            if isinstance(v, LogicalOp):
                op_walk(v)
            elif isinstance(v, E.Expr):
                expr_walk(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, LogicalOp):
                        op_walk(x)
                    elif isinstance(x, E.Expr):
                        expr_walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, LogicalOp):
                                op_walk(y)
                            elif isinstance(y, E.Expr):
                                expr_walk(y)

    op_walk(plan)
    if bad:
        return None
    if not slots:
        return []
    if sorted(slots) != list(range(len(slots))):
        return None  # non-dense slots: stay on the legacy tuple
    spec = []
    off = 0
    for i in range(len(slots)):
        dt = slots[i]
        w = (int(dt.precision) if dt.kind is TypeKind.VECTOR else 1)
        spec.append((dt, off, w))
        off += w
    return spec


def packed_width(spec) -> int:
    """Total int64 lanes of a packed qparam vector for `spec`."""
    if not spec:
        return 0
    _dt, off, w = spec[-1]
    return off + w


def _unpack_qparams(qparams, spec):
    """Inside the traced program: rebuild the per-slot value tuple from
    the packed int64 vector (floats ride as bitcast bits; VECTOR slots
    come back as (d,) arrays)."""
    if not isinstance(qparams, jnp.ndarray):
        return qparams  # legacy tuple path (PX, chunked, direct callers)
    if spec is None:
        raise AssertionError("packed qparams without a pack spec")
    out = []
    for dt, off, w in spec:
        if dt.kind is TypeKind.VECTOR:
            raw = jax.lax.dynamic_slice_in_dim(qparams, off, w)
            v = jax.lax.bitcast_convert_type(raw, jnp.float64)
            out.append(v.astype(dt.storage_np))
            continue
        raw = qparams[off]
        if dt.is_float:
            v = jax.lax.bitcast_convert_type(raw, jnp.float64)
            out.append(v.astype(dt.storage_np))
        else:
            out.append(raw.astype(dt.storage_np))
    return tuple(out)


def pack_qparams(values, dtypes, spec) -> "np.ndarray | tuple":
    """Host side of the packed-parameter ABI: one int64 vector for the
    whole parameter set (or the legacy tuple when the spec opted out)."""
    if spec is None or len(spec) != len(values):
        import jax.numpy as _jnp

        return tuple(
            _jnp.asarray(bind_value(v, t)) for v, t in zip(values, dtypes)
        )
    out = np.empty(packed_width(spec), dtype=np.int64)
    for (t, off, w), v in zip(spec, values):
        if w != 1:
            # VECTOR slot: parse + dim-check once on the host, widen each
            # float32 component to float64 bits so the device-side bitcast
            # is uniform across slot kinds
            a = np.asarray(bind_value(v, t), dtype=np.float64)
            out[off:off + w] = a.view(np.int64)
            continue
        if type(v) is int:
            # integer literal into an integer slot: the generic path costs
            # three numpy scalar hops per parameter, and this is THE shape
            # of a point read. Assignment range-checks against int64;
            # int32 slots get the same explicit bound bind_value enforces.
            k = t.kind
            if k is TypeKind.INT64:
                out[off] = v
                continue
            if k is TypeKind.INT32 and -2147483648 <= v <= 2147483647:
                out[off] = v
                continue
        s = bind_value(v, t)
        a = np.asarray(s)
        if a.dtype.kind == "f":
            out[off] = np.float64(a).view(np.int64)
        else:
            out[off] = np.int64(a)
    return out


def _narrow_seed(plan, default_rows: int) -> int:
    """Row-count seed for the fused result-narrowing frame: how many live
    rows the client can actually receive from this plan root. LIMIT/TopN
    roots bound it exactly (n + offset — the engine's limit op keeps the
    offset rows live and the cursor slices); a group-less aggregate yields
    one row; everything else falls back to the caller's default (grown on
    narrow-overflow like any other static capacity)."""
    node = plan
    while isinstance(node, Project):
        node = node.child
    if isinstance(node, (Limit, TopN)):
        return max(1, int(node.n) + int(getattr(node, "offset", 0) or 0))
    if isinstance(node, Aggregate) and not node.group_keys and (
        getattr(node, "grouping_sets", None) is None
    ):
        return 1
    return max(1, int(default_rows))


class PreparedPlan:
    """A compiled plan: jitted XLA program + static capacities. Re-runnable;
    transparently recompiles at larger capacities on overflow."""

    def __init__(self, executor, plan, params, jitted, input_spec, overflow_nodes):
        self.executor = executor
        self.plan = plan
        self.params = params
        self.jitted = jitted
        self.input_spec = input_spec
        self.overflow_nodes = overflow_nodes
        self.retries = 0  # lifetime overflow-recompile count (plan monitor)
        self._qparam_spec = _collect_qparam_spec(plan)
        # cross-session micro-batching: pow2 bucket -> vmapped executable
        # (cleared by recompile(): a capacity bump makes them stale)
        self._batched: dict[int, object] = {}
        # whole-statement fusion: pow2 narrow cap -> fused executable that
        # inlines the plan program AND the result-frame gather into ONE
        # dispatch (cleared by recompile() like the batched buckets)
        self._narrow: dict[int, object] = {}
        self._narrow_cap = 0   # current pow2 frame width (0 = unseeded)
        self._narrow_off = False  # result too wide for fusion: plain path
        # persistent-artifact state (engine/plan_artifact.py): True means
        # jitted is a live traceable jit (vmap-able for batched buckets);
        # False means it is a deserialized AOT executable that must
        # recompile before any new trace. artifact_ref = (store, aid)
        # once this plan has an on-disk artifact.
        self._traceable = True
        self.artifact_ref = None
        # compile-time optimizer row estimates per node id (filled by
        # prepare(); restored from ArtifactMeta on warm hydrate) — the
        # estimate half of the operator profiler's calibration pairs
        self.node_estimates: dict[int, int] = {}

    def bind(self, values, dtypes):
        """Values -> the dispatch form (one packed int64 vector when the
        plan's parameter set allows it — one upload instead of N)."""
        return pack_qparams(values, dtypes, self._qparam_spec)

    def recompile(self) -> None:
        """Refresh the jitted executable after a capacity/spec change.
        EVERY recompile path must come through here: the batched bucket
        executables close over the old capacities and must drop with it."""
        self.jitted, self.input_spec, self.overflow_nodes = (
            self.executor.compile(self.plan, self.params)
        )
        self._batched.clear()
        self._narrow.clear()
        self._traceable = True
        # mesh executors rebuild their exchange recorder per compile; the
        # cached plan must follow the fresh one or its mesh plan (worker
        # spans, collective counters) would freeze at the old capacities
        sync = getattr(self.executor, "sync_prepared", None)
        if sync is not None:
            sync(self)
        if self.artifact_ref is not None:
            # the executable just changed capacity under a persisted
            # artifact: re-export at the new capacity, or the overflow
            # replays on every warm boot
            try:
                self.artifact_ref[0].on_recompile(self)
            except Exception:
                pass

    def _inputs(self):
        try:
            return {
                alias: self.executor.input_batch(alias, table, cols)
                for alias, table, cols in self.input_spec
            }
        except ClusteredPremiseInvalidated:
            # the probe's clustering dissolved under a cached plan:
            # recompile (spec re-detection drops the fast path) and
            # assemble again
            self.recompile()
            return {
                alias: self.executor.input_batch(alias, table, cols)
                for alias, table, cols in self.input_spec
            }

    def jit_call(self, inputs, qparams):
        """Every dispatch funnels through here. A warm (artifact-loaded)
        executable validates its input signature per call; any drift (a
        table's device capacity moved since export) raises ArtifactStale
        and we recompile from the logical plan — one honest compile,
        never a stale program over wrong-shaped buffers."""
        from .plan_artifact import ArtifactStale

        try:
            return self.jitted(inputs, qparams)
        except ArtifactStale:
            self.recompile()
            return self.jitted(self._inputs(), qparams)

    def run_nocheck(self, qparams: tuple = ()):
        """Dispatch one execution WITHOUT the overflow host sync — for
        benchmarking/pipelining after a checked run validated capacities."""
        out, _ovf = self.jit_call(self._inputs(), qparams)
        return out

    def run(self, max_retries: int = 3, qparams: tuple = ()):
        from ..share.interrupt import checkpoint

        for attempt in range(max_retries + 1):
            checkpoint()  # between overflow retries (and before the first run)
            inputs = self._inputs()
            out, ovf_vec = self.jit_call(inputs, qparams)
            overflows = self._overflows(np.asarray(ovf_vec))  # ONE fetch
            if not overflows:
                return out
            if attempt == max_retries:
                raise RuntimeError(
                    f"capacity overflow after {max_retries} retries: {overflows}"
                )
            self.retries += 1
            self.params.bump(overflows)
            self.recompile()
        raise AssertionError

    def _overflows(self, hovf) -> dict:
        return {
            nid: int(v)
            for nid, v in zip(self.overflow_nodes, hovf)
            if int(v) > 0
        }

    def run_host(self, max_retries: int = 3, qparams: tuple = ()):
        """Dispatch + fetch EVERYTHING (result columns, validity, sel,
        overflow counters) in ONE device_get. The separate run() +
        batch_to_host path costs one tunnel roundtrip per array; for a
        short query those roundtrips dominate end-to-end latency. Returns
        (host_cols, host_valid, host_sel, schema, dicts)."""
        import jax as _jax

        from ..share.interrupt import checkpoint

        for attempt in range(max_retries + 1):
            checkpoint()
            inputs = self._inputs()
            out, ovf_vec = self.jit_call(inputs, qparams)
            hovf, hcols, hvalid, hsel = _jax.device_get(
                (ovf_vec, out.cols, out.valid, out.sel))
            overflows = self._overflows(hovf)
            if not overflows:
                return hcols, hvalid, hsel, out.schema, out.dicts
            if attempt == max_retries:
                raise RuntimeError(
                    f"capacity overflow after {max_retries} retries: "
                    f"{overflows}")
            self.retries += 1
            self.params.bump(overflows)
            self.recompile()
        raise AssertionError

    def run_device(self, qparams: tuple = ()):
        """Dispatch WITHOUT any host sync: returns device references
        (out ColumnBatch, overflow vector). JAX async dispatch returns as
        soon as the program is enqueued, so the caller's host work
        (audit, metrics, trace assembly) overlaps device compute; the
        overflow check moves to the first fetch (DeviceResult._sync)."""
        from ..share.interrupt import checkpoint

        checkpoint()
        return self.jit_call(self._inputs(), qparams)

    # ---- whole-statement fusion (result narrowing) --------------------
    def narrow_frame(self, default_rows: int, max_rows: int) -> int:
        """Pow2 width of the fused result frame, or 0 when this plan has
        opted out (result provably wider than the ceiling, or a prior
        narrow run overflowed past it). Seeded from the plan root
        (LIMIT/aggregate bounds), clamped to the root-compaction capacity
        — narrowing past what compact_batch already emits moves no fewer
        bytes."""
        if self._narrow_off:
            return 0
        ncap = self._narrow_cap
        if ncap == 0:
            ncap = next_pow2(_narrow_seed(self.plan, default_rows))
            root = self.params.join_cap.get(ROOT_COMPACT)
            if root:
                ncap = min(ncap, next_pow2(int(root)))
            self._narrow_cap = ncap
        if ncap > max_rows:
            self._narrow_off = True
            return 0
        return ncap

    def _build_narrow(self, ncap: int):
        """One jitted program = the plan program (inlined: calling the
        live jit inside jit fuses the traces, same mechanism as the
        batched buckets' vmap) + the final result-frame gather. The
        stable-ascending nonzero keeps live rows in their original
        relative order, so the frame is bit-identical to the plain
        path's host-side sel masking."""
        inner = self.jitted

        def run_narrow(inputs, qparams):
            out, ovf_vec = inner(inputs, qparams)
            nlive = jnp.sum(out.sel, dtype=jnp.int64)
            idx = jnp.nonzero(out.sel, size=ncap, fill_value=0)[0]
            cols = {n: jnp.take(c, idx, axis=0)
                    for n, c in out.cols.items()}
            valid = {n: jnp.take(v, idx, axis=0)
                     for n, v in out.valid.items()}
            nkeep = jnp.minimum(nlive, jnp.int64(ncap))
            lanes = jnp.arange(ncap, dtype=jnp.int64) < nkeep
            nb = ColumnBatch(cols=cols, valid=valid, sel=lanes,
                             nrows=nkeep, schema=out.schema,
                             dicts=out.dicts)
            return nb, ovf_vec, jnp.maximum(nlive - ncap, 0)

        return jax.jit(run_narrow)

    def run_device_narrow(self, qparams: tuple, ncap: int):
        """Fused dispatch WITHOUT host sync: returns (narrowed ColumnBatch,
        plan overflow vector, narrow-overflow scalar) as device refs —
        ONE enqueued program covering predicate through final frame, so
        the statement's only host roundtrip is NarrowDeviceResult's
        completion sync."""
        from ..share.interrupt import checkpoint

        from .plan_artifact import ArtifactStale

        checkpoint()
        for _attempt in range(3):
            fn = self._narrow.get(ncap)
            if fn is None:
                if not self._traceable:
                    # AOT-deserialized executable: cannot re-trace inside
                    # a fresh jit — one honest recompile restores
                    # traceability (the backend hits the XLA disk cache)
                    self.recompile()
                # build + first-trace under the lock: tracing re-enters
                # plan emission's process-global parameter frame, exactly
                # like the batched buckets
                with _BATCH_COMPILE_LOCK:
                    fn = self._narrow.get(ncap)
                    if fn is None:
                        fn = self._build_narrow(ncap)
                        self.executor.narrow_compiles += 1
                        try:
                            res = fn(self._inputs(), qparams)
                        except ArtifactStale:
                            self.recompile()
                            continue
                        self._narrow[ncap] = fn
                        return res
            try:
                return fn(self._inputs(), qparams)
            except ArtifactStale:
                self._narrow.pop(ncap, None)
                self.recompile()
        raise RuntimeError("narrowed executable stale after recompiles")

    # ---- cross-session micro-batching ---------------------------------
    @property
    def batchable(self) -> bool:
        """Eligible for the statement micro-batcher: the plan rides the
        packed int64 qparam ABI with at least one slot (a 0-slot plan has
        nothing to vary per lane — every concurrent hit is the SAME
        dispatch and the solo path already amortizes it via the XLA
        result cache; vector/legacy-tuple plans opted out of packing)."""
        return bool(self._qparam_spec)

    def run_batched_host(self, qblock: np.ndarray, max_retries: int = 3):
        """ONE device dispatch for B same-plan statements: `qblock` is
        the [B, nslots] stack of packed parameter vectors. The executable
        is `vmap` over the packed-parameter argument only (in_axes=(None,
        0)) — the scan/shared subplan traces against un-batched inputs,
        so XLA sees one pass over the data and per-lane work only where a
        predicate/projection actually consumes a parameter.

        B pads to a power-of-two bucket (repeat lane 0: a duplicate query
        whose lane is never scattered back) so the number of XLA
        compilations is bounded by the bucket count regardless of traffic
        shape. Returns (hcols, hvalid, hsel, schema, dicts) with a
        leading [bucket] axis on every array — the caller scatters lane i
        to waiting session i. Overflow on ANY lane redrives the shared
        bump/recompile loop (max over lanes, exactly what run_host does
        for one)."""
        from ..share.interrupt import checkpoint

        b = int(qblock.shape[0])
        bucket = next_pow2(b)
        if bucket > b:
            qblock = np.concatenate(
                [qblock, np.repeat(qblock[:1], bucket - b, axis=0)])
        from .plan_artifact import ArtifactStale

        for attempt in range(max_retries + 1):
            checkpoint()
            fn = self._batched.get(bucket)
            if fn is None and not self._traceable:
                # warm (artifact-loaded) plan: vmap over a deserialized
                # call is unsupported, so hydrate the persisted bucket
                # variant if one exists; else restore traceability with
                # one honest recompile (counted; the backend compile hits
                # the XLA disk cache) and build below as usual
                store = self.artifact_ref[0] if self.artifact_ref else None
                fn = (store.load_bucket(self, bucket)
                      if store is not None else None)
                if fn is not None:
                    self._batched[bucket] = fn
                else:
                    self.recompile()
            if fn is None:
                # build + first-trace under the lock: tracing re-enters
                # plan emission, which installs the process-global active
                # parameter frame (expr.compile.set_params) — two leaders
                # tracing concurrently would cross their frames
                with _BATCH_COMPILE_LOCK:
                    fn = self._batched.get(bucket)
                    if fn is None:
                        fn = jax.jit(jax.vmap(self.jitted,
                                              in_axes=(None, 0)))
                        self.executor.batched_compiles += 1
                        out, ovf_vec = fn(self._inputs(), qblock)
                        self._batched[bucket] = fn
                        if self.artifact_ref is not None:
                            try:
                                self.artifact_ref[0].export_bucket(
                                    self, bucket, fn)
                            except Exception:
                                pass
                    else:
                        out, ovf_vec = fn(self._inputs(), qblock)
            else:
                try:
                    out, ovf_vec = fn(self._inputs(), qblock)
                except ArtifactStale:
                    # catalog drift under a hydrated bucket executable:
                    # drop it and redrive through a clean rebuild
                    self._batched.pop(bucket, None)
                    self.recompile()
                    continue
            hovf, hcols, hvalid, hsel = jax.device_get(
                (ovf_vec, out.cols, out.valid, out.sel))
            overflows = self._overflows(np.asarray(hovf).max(axis=0))
            if not overflows:
                return hcols, hvalid, hsel, out.schema, out.dicts
            if attempt == max_retries:
                raise RuntimeError(
                    f"capacity overflow after {max_retries} retries: "
                    f"{overflows}")
            self.retries += 1
            self.params.bump(overflows)
            self.recompile()
        raise AssertionError


# serializes batched-bucket trace/compile across leader threads (see
# PreparedPlan.run_batched_host)
_BATCH_COMPILE_LOCK = threading.Lock()


# fetch_head's compaction gather, jitted with a STATIC width so the
# executable is shared across results of the same shape. The trace
# counter is a mutable cell bumped inside the traced body: it moves only
# when XLA actually (re)compiles, which is what the regression test
# pins — distinct LIMIT values within one pow2 bucket must not retrace.
_head_gather_traces = [0]


def _head_gather_impl(cols, valid, sel, k):
    _head_gather_traces[0] += 1
    idx = jnp.nonzero(sel, size=k, fill_value=0)[0]
    return (
        {n: jnp.take(c, idx) for n, c in cols.items()},
        {n: jnp.take(v, idx) for n, v in valid.items()},
    )


_head_gather = jax.jit(_head_gather_impl, static_argnums=(3,))


class DeviceResult:
    """Lazy device-resident result cursor (the serving-path half of the
    fast path: `SELECT ... LIMIT 10` over a 60M-row result must transfer
    KB, not GB).

    The first host access fetches ONLY the overflow counters and the live
    row count (two scalars — this is the async-dispatch sync point; a
    capacity overflow redrives the recompile loop here, exactly as
    run_host's eager loop would have). Column data transfers on demand:
    per touched column, or LIMIT-bounded via a device-side compaction
    gather when the caller wants the first k rows of a large result."""

    def __init__(self, prepared, qparams, out, ovf_vec, max_retries: int = 3,
                 profile=None, phases=None):
        self.prepared = prepared
        self._qparams = qparams
        self._out = out
        self._ovf = ovf_vec
        self._max_retries = max_retries
        # observability hooks, updated in place as transfers happen:
        # server/diag.QueryProfile (fetch_s / d2h_bytes) and the session's
        # last_phases dict for this statement
        self.profile = profile
        self.phases = phases
        self._nrows: int | None = None
        self._hcols: dict = {}
        self._hvalid: dict = {}
        self._hsel = None

    def _observe(self, seconds: float, nbytes: int,
                 kind: str = "sync") -> None:
        if self.profile is not None:
            self.profile.fetch_s += seconds
            self.profile.d2h_bytes += nbytes
        if self.phases is not None:
            self.phases["fetch_s"] = self.phases.get("fetch_s", 0.0) + seconds
            if kind == "d2h":
                # column-data transfers, split out of the dispatch sync so
                # the host-tax ledger can carve "d2h" from "device wait"
                self.phases["d2h_s"] = (
                    self.phases.get("d2h_s", 0.0) + seconds)

    def _sync(self) -> None:
        """Overflow check + row count: the deferred tail of the dispatch.
        Runs the same bump/recompile/redrive loop as PreparedPlan.run."""
        if self._nrows is not None:
            return
        import time as _time

        from ..share.interrupt import checkpoint

        p = self.prepared
        # serving-latency fold: when the whole result footprint is small
        # (known from the per-executable memo), piggyback the column data
        # on the completion sync — ONE host roundtrip instead of a second
        # device_get when the client fetches. Big results keep the lazy
        # contract (transfer only what's touched).
        rmemo = getattr(p, "_result_bytes_memo", None)
        small = (rmemo is not None and rmemo[0] == getattr(p, "retries", 0)
                 and rmemo[1] <= 65536 and not self._hcols
                 and self._hsel is None)
        for attempt in range(self._max_retries + 1):
            t0 = _time.perf_counter()
            if small:
                # per-leaf np.asarray: same blocking semantics, none of
                # device_get's pytree + async-batching overhead (~16us a
                # statement for a handful of KB-sized leaves). The device
                # nrows scalar is sum(sel); with sel crossing anyway the
                # sum runs host-side — one fewer transfer leaf.
                hovf = np.asarray(self._ovf)
                harrs = {n: np.asarray(a)
                         for n, a in self._out.cols.items()}
                hvals = {n: np.asarray(a)
                         for n, a in self._out.valid.items()}
                hsel = np.asarray(self._out.sel)
                hn = int(hsel.sum())
            else:
                hovf = np.asarray(self._ovf)
                hn = int(np.asarray(self._out.nrows))
            self._observe(_time.perf_counter() - t0,
                          int(getattr(hovf, "nbytes", 0)) + 8)
            overflows = p._overflows(np.asarray(hovf))
            if not overflows:
                self._nrows = int(hn)
                if small:
                    # commit ONLY on a clean run: an overflowed attempt's
                    # arrays are garbage and must not seed the host cache
                    self._hcols.update(harrs)
                    self._hvalid.update(hvals)
                    self._hsel = np.asarray(hsel)
                    self._observe(0.0, sum(
                        int(getattr(a, "nbytes", 0))
                        for d in (harrs, hvals) for a in d.values()
                    ) + int(self._hsel.nbytes))
                return
            if attempt == self._max_retries:
                raise RuntimeError(
                    f"capacity overflow after {self._max_retries} retries: "
                    f"{overflows}")
            p.retries += 1
            p.params.bump(overflows)
            p.recompile()
            checkpoint()
            self._out, self._ovf = p.jit_call(p._inputs(), self._qparams)

    @property
    def nrows(self) -> int:
        self._sync()
        return self._nrows

    @property
    def schema(self):
        return self._out.schema

    @property
    def dicts(self):
        return self._out.dicts

    def fetch_columns(self, names=None) -> dict:
        """Host rows (sel-compacted, dict-decoded) for the requested
        columns — all of them when names is None. Each column transfers
        at most once; repeats serve from the host cache."""
        import time as _time

        from ..core.column import host_rows

        self._sync()
        fields = [f for f in self._out.schema.fields
                  if names is None or f.name in names]
        need = [f.name for f in fields if f.name not in self._hcols]
        if need or self._hsel is None:
            arrs = {n: self._out.cols[n] for n in need}
            vals = {n: self._out.valid[n] for n in need
                    if n in self._out.valid}
            t0 = _time.perf_counter()
            sel_fetched = self._hsel is None
            if sel_fetched:
                harrs, hvals, hsel = jax.device_get(
                    (arrs, vals, self._out.sel))
                self._hsel = np.asarray(hsel)
            else:
                harrs, hvals = jax.device_get((arrs, vals))
            nbytes = sum(int(getattr(a, "nbytes", 0))
                         for d in (harrs, hvals) for a in d.values())
            if sel_fetched:
                nbytes += int(self._hsel.nbytes)
            self._observe(_time.perf_counter() - t0, nbytes,
                          kind="d2h")
            self._hcols.update(harrs)
            self._hvalid.update(hvals)
        sub = Schema(tuple(fields))
        return host_rows(sub, self._out.dicts, self._hcols, self._hvalid,
                         self._hsel)

    def fetch_head(self, limit: int) -> dict:
        """First `limit` live rows via a device-side compaction gather:
        ~k rows per column cross the link instead of the full static
        capacity. The gather width buckets to a power of two so a client
        sweeping LIMIT values (pagination) reuses log2(cap) executables
        instead of compiling one per distinct k. Serves from the host
        cache when a full fetch already happened."""
        import time as _time

        from ..core.column import host_rows

        self._sync()
        k = min(int(limit), self._nrows)
        if self._hsel is not None and not (
            set(f.name for f in self._out.schema.fields) - set(self._hcols)
        ):
            host = host_rows(self._out.schema, self._out.dicts, self._hcols,
                             self._hvalid, self._hsel)
            return {n: v[:k] for n, v in host.items()}
        cap = int(self._out.sel.shape[-1])
        kb = min(next_pow2(max(k, 1)), cap)
        arrs, vals = _head_gather(self._out.cols, self._out.valid,
                                  self._out.sel, kb)
        t0 = _time.perf_counter()
        harrs, hvals = jax.device_get((arrs, vals))
        nbytes = sum(int(getattr(a, "nbytes", 0))
                     for d in (harrs, hvals) for a in d.values())
        self._observe(_time.perf_counter() - t0, nbytes, kind="d2h")
        host = host_rows(self._out.schema, self._out.dicts, harrs, hvals,
                         np.ones(kb, dtype=np.bool_))
        return {n: v[:k] for n, v in host.items()}


class NarrowDeviceResult(DeviceResult):
    """DeviceResult over a FUSED narrowed dispatch: `out` is the final
    ncap-row result frame (plan program + compaction gather in one XLA
    program), so the completion sync fetches the entire client-visible
    payload in one host roundtrip — no separate d2h leg and no
    O(capacity) host result fold. A frame overflow grows the pow2 width
    and redrives; past the configured ceiling the plan surrenders fusion
    and this cursor falls back to the plain lazy contract."""

    narrowed = True

    def __init__(self, prepared, qparams, out, ovf_vec, novf, ncap: int,
                 narrow_max: int, max_retries: int = 3, profile=None,
                 phases=None):
        super().__init__(prepared, qparams, out, ovf_vec,
                         max_retries=max_retries, profile=profile,
                         phases=phases)
        self._novf = novf
        self._ncap = int(ncap)
        self._narrow_max = int(narrow_max)
        self._fallback = False

    def _sync(self) -> None:
        if self._nrows is not None:
            return
        if self._fallback:
            return super()._sync()
        import time as _time

        from ..share.interrupt import checkpoint

        p = self.prepared
        for attempt in range(self._max_retries + 1):
            t0 = _time.perf_counter()
            # the frame IS the result: per-leaf blocking np.asarray of
            # overflow counters + every (ncap-row) leaf — the base small
            # path's one-roundtrip shape, made unconditional by the fused
            # program having already bounded the frame
            hovf = np.asarray(self._ovf)
            hnovf = int(np.asarray(self._novf))
            harrs = {n: np.asarray(a) for n, a in self._out.cols.items()}
            hvals = {n: np.asarray(a) for n, a in self._out.valid.items()}
            hsel = np.asarray(self._out.sel)
            self._observe(_time.perf_counter() - t0,
                          int(getattr(hovf, "nbytes", 0)) + 8)
            overflows = p._overflows(np.asarray(hovf))
            if not overflows and hnovf == 0:
                self._nrows = int(hsel.sum())
                # commit ONLY on a clean run (overflowed frames are
                # garbage), same contract as the base small path
                self._hcols.update(harrs)
                self._hvalid.update(hvals)
                self._hsel = hsel
                self._observe(0.0, sum(
                    int(getattr(a, "nbytes", 0))
                    for d in (harrs, hvals) for a in d.values()
                ) + int(hsel.nbytes))
                return
            if attempt == self._max_retries:
                raise RuntimeError(
                    f"capacity overflow after {self._max_retries} "
                    f"retries: {overflows or {'narrow': hnovf}}")
            if overflows:
                p.retries += 1
                p.params.bump(overflows)
                p.recompile()
            if hnovf > 0:
                grown = next_pow2(self._ncap + hnovf)
                p._narrow_cap = max(p._narrow_cap, grown)
                if grown > self._narrow_max:
                    # frame too wide to fuse: remember on the plan (next
                    # warm hit skips fusion outright) and finish THIS
                    # statement on the plain path
                    p._narrow_off = True
                    self._fallback = True
                    checkpoint()
                    self._out, self._ovf = p.jit_call(
                        p._inputs(), self._qparams)
                    return super()._sync()
                self._ncap = grown
            checkpoint()
            self._out, self._ovf, self._novf = p.run_device_narrow(
                self._qparams, self._ncap)
        raise AssertionError


def _range_bounds(c: E.Expr, qual: str) -> list:
    """Classify one conjunct as bounds on column `qual`: a list of
    ('gt'|'ge'|'lt'|'le'|'eq', Literal) pairs (empty = not a bound).
    Handles both operand orders and non-negated BETWEEN."""
    if isinstance(c, E.Between) and not c.negated:
        if (
            isinstance(c.arg, E.ColRef) and c.arg.name == qual
            and isinstance(c.low, E.Literal)
            and isinstance(c.high, E.Literal)
        ):
            return [("ge", c.low), ("le", c.high)]
        return []
    if not isinstance(c, E.Compare):
        return []
    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
    op, lhs, rhs = c.op, c.left, c.right
    if isinstance(rhs, E.ColRef) and isinstance(lhs, E.Literal):
        op, lhs, rhs = flip.get(op), rhs, lhs
    if not (
        isinstance(lhs, E.ColRef) and lhs.name == qual
        and isinstance(rhs, E.Literal) and op in flip
    ):
        return []
    kind = {"<": "lt", "<=": "le", ">": "gt", ">=": "ge", "=": "eq"}[op]
    return [(kind, rhs)]


def _slice_sorted_scan(qb: ColumnBatch, sl: _SliceSpec, cap: int, n: int):
    """Read only the qualifying key range of a sorted-projection scan.

    Device binary search finds [lo, hi) from the (possibly parameterized)
    bounds, one dynamic_slice per column reads `cap` rows from lo, and
    rows outside [lo, hi) mask off. Returns (sliced batch, overflow =
    max(hi-lo-cap, 0)) — a runtime range wider than the static capacity
    rides the usual overflow-retry recompile. The TPU redesign of the
    reference's index range scan (ob_das_scan_op.h): the 'index' is the
    projection's physical order, the 'scan range' a device slice."""
    from ..expr.compile import literal_scalar

    kcol = jax.lax.slice_in_dim(qb.cols[sl.key], 0, n)  # drop capacity pad
    lo = jnp.zeros((), jnp.int64)
    hi = jnp.full((), n, jnp.int64)
    for lit, side in sl.lows:
        v = literal_scalar(lit).astype(kcol.dtype)
        lo = jnp.maximum(
            lo, jnp.searchsorted(kcol, v, side=side).astype(jnp.int64)
        )
    for lit, side in sl.highs:
        v = literal_scalar(lit).astype(kcol.dtype)
        hi = jnp.minimum(
            hi, jnp.searchsorted(kcol, v, side=side).astype(jnp.int64)
        )
    hi = jnp.maximum(hi, lo)
    cap2 = qb.capacity
    start = jnp.clip(lo, 0, cap2 - cap)
    gidx = start + jnp.arange(cap, dtype=jnp.int64)
    in_range = (gidx >= lo) & (gidx < hi)

    def dsl(c):
        return jax.lax.dynamic_slice_in_dim(c, start, cap)

    cols = {k: dsl(c) for k, c in qb.cols.items()}
    valid = {k: dsl(c) for k, c in qb.valid.items()}
    sel = dsl(qb.sel) & in_range
    out = ColumnBatch(
        cols=cols,
        valid=valid,
        sel=sel,
        nrows=jnp.sum(sel, dtype=jnp.int64),
        schema=qb.schema,
        dicts=qb.dicts,
    )
    return out, jnp.maximum((hi - lo) - cap, 0)


def _affine_candidates(probe_key, aff, nb):
    """Direct-address candidate build rows against an affine build key
    column: cand = (key - a0) / stride — no sorts, no gathers. Callers
    verify via gathered build key + liveness (folded into the packed
    payload gather so the verify costs no extra gather pass)."""
    a0, stride = aff
    off = probe_key.astype(jnp.int64) - a0
    cand = off // stride
    in_range = (off >= 0) & (off % stride == 0) & (cand < nb)
    candc = jnp.clip(cand, 0, nb - 1).astype(jnp.int32)
    return candc, in_range


def _affine_probe(build_key, build_sel, probe_key, probe_sel, aff):
    """Verified affine probe for callers that need ONLY the match row
    (semi/anti). The verify gather rides one packed row-gather."""
    candc, in_range = _affine_candidates(probe_key, aff, build_key.shape[0])
    got = gather_payload(
        {"#k": build_key}, {}, candc, build_sel
    )
    hit = (
        probe_sel & in_range
        & (got[0]["#k"] == probe_key)
        & got[2]
    )
    return jnp.where(hit, candc, -1)


def _direct_slot_agg(op: str, slot_is, mask, values):
    """One aggregate over a small packed-key domain as fused masked
    reductions (the scatter-free direct group-by)."""
    if op == "count":
        return jnp.stack(
            [jnp.sum(mask & g, dtype=jnp.int64) for g in slot_is]
        )
    if op == "sum":
        acc = (
            jnp.int64
            if jnp.issubdtype(values.dtype, jnp.integer)
            else values.dtype
        )
        return jnp.stack([
            jnp.sum(jnp.where(mask & g, values, 0).astype(acc))
            for g in slot_is
        ])
    if op == "min":
        ident = (
            jnp.iinfo(values.dtype).max
            if jnp.issubdtype(values.dtype, jnp.integer)
            else jnp.inf
        )
        return jnp.stack([
            jnp.min(jnp.where(mask & g, values, ident)) for g in slot_is
        ])
    if op == "max":
        ident = (
            jnp.iinfo(values.dtype).min
            if jnp.issubdtype(values.dtype, jnp.integer)
            else -jnp.inf
        )
        return jnp.stack([
            jnp.max(jnp.where(mask & g, values, ident)) for g in slot_is
        ])
    raise NotImplementedError(op)


def _join_schema(ls: Schema, rs: Schema) -> Schema:
    return Schema(tuple(list(ls.fields) + list(rs.fields)))


def _agg_schema(op: Aggregate, child_schema: Schema) -> Schema:
    fields = []
    gs = op.grouping_sets
    for i, (name, e) in enumerate(op.group_keys):
        t = infer_type(e, child_schema)
        if gs is not None and any(i not in s for s in gs):
            t = replace(t, nullable=True)  # NULL-filled in coarser sets
        fields.append(Field(name, t))
    for name, fn, arg, _ in op.aggs:
        if fn in ("count", "approx_ndv"):
            fields.append(Field(name, DataType.int64()))
        else:
            t = infer_type(arg, child_schema)
            if fn == "sum" and t.is_decimal:
                t = DataType.decimal(18, t.scale)
            elif fn == "sum" and t.is_integer:
                t = DataType.int64()
            fields.append(Field(name, t))
    return Schema(tuple(fields))
