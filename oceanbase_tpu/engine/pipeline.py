"""Streaming pipeline engine: the out-of-core data path that decouples
data scale from HBM.

Reference surface: the reference engine's DTL-fed pipelined operators and
ObHJPartition (sql/engine/join/hash_join) crossed with Tailwind's "keep
the accelerator saturated" discipline — the device must never idle on the
host<->device wire, and the wire must never carry bytes the storage
encodings already removed.

Three mechanisms, composed by engine/chunked.ChunkedPreparedPlan:

  1. double-buffered H2D prefetch (ChunkPrefetcher): while chunk k's
     program computes, chunk k+1 is already host-encoded and its
     device_put is in flight on a staging thread. The queue depth bounds
     in-flight staged chunks; every staged chunk holds a governor staged
     lease so host-pinned wire buffers are accounted (and provably
     released — the ledger balances even when a statement dies with a
     prefetch in flight).

  2. compressed chunk streaming with decode-on-device (ChunkStager +
     _decode_staged): each streamed column freezes a per-column *wire
     plan* on first chunk — FOR (frame-of-reference at byte width), RLE
     (run values + run lengths at a frozen power-of-two run capacity) or
     raw — chosen by the same cost model the storage encodings use
     (storage/encoding.choose_encoding). The H2D transfer carries the
     encoded form; ONE jitted kernel expands it on device (FOR: widen +
     add base; RLE: cumsum + searchsorted gather; validity: bit-unpack),
     so the wire bytes shrink by the encoding ratio while the device
     program still sees full-width columns. A chunk that falls outside
     its frozen frame (narrow overflow / run-cap overflow) ships raw for
     that chunk — one recompile, never a wrong answer, mirroring the
     _narrow_plan fallback discipline.

  3. grace-hash partitioned join/group-by (GraceHashPreparedPlan): when
     the BUILD side also exceeds the budget (chunked.NotStreamable), both
     sides hash-partition by a join key to host tmp-file segments
     (storage/tmp_file), and ONE static device program — the split
     subtree over fixed-capacity $live-masked overlay tables — streams
     the partition pairs. Partition counts derive from the governor's
     remaining budget. Group-by mode partitions a single table by a
     GROUP BY key, which makes even non-mergeable aggregates (count
     distinct) exactly computable per partition: groups are
     partition-disjoint, so the merge is pure concatenation.

Overlap is measured, not assumed: OverlapMeter does exact interval-union
accounting of h2d-busy vs compute-busy wall time; the fraction surfaces
in __all_virtual_sql_plan_monitor.h2d_overlap_pct, the "stream h2d
overlap" sysstat counter and the serving timeline's per-bucket
h2d_overlap_frac.
"""

from __future__ import annotations

import queue
import threading
import time
from contextlib import contextmanager
from dataclasses import replace as dc_replace
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtypes import DataType, Field, Schema
from ..core.table import Table
from ..expr import ir as E
from ..sql.logical import (
    Aggregate,
    Filter,
    JoinOp,
    Project,
    Scan,
    output_schema,
)
from ..share import gap_ledger as _gap
from ..storage.encoding import ENC_FOR, ENC_RLE, analyze_ints, choose_encoding

# ---------------------------------------------------------------------------
# telemetry


class StreamStats:
    """Cumulative streaming counters carried by a prepared plan; the
    session folds per-run deltas into the plan monitor / sysstat /
    timeline (snapshot-diff, like overflow retries)."""

    __slots__ = ("chunks", "staged_bytes", "decoded_bytes", "h2d_s",
                 "compute_s", "overlap_s", "spill_partitions")

    def __init__(self):
        self.chunks = 0
        self.staged_bytes = 0
        self.decoded_bytes = 0
        self.h2d_s = 0.0
        self.compute_s = 0.0
        self.overlap_s = 0.0
        self.spill_partitions = 0

    @property
    def h2d_overlap_pct(self) -> float:
        return 100.0 * self.overlap_s / self.h2d_s if self.h2d_s else 0.0

    def snapshot(self) -> tuple:
        return (self.chunks, self.staged_bytes, self.decoded_bytes,
                self.h2d_s, self.compute_s, self.overlap_s,
                self.spill_partitions)


class OverlapMeter:
    """Exact interval-union accounting of two activity sides ("h2d" and
    "compute"): on every enter/exit event the elapsed slice since the
    previous event is credited to whichever sides were active — and to
    `overlap_s` when both were. Thread-safe (the prefetch thread meters
    h2d while the consumer meters compute)."""

    def __init__(self, clock=time.perf_counter):
        self._lock = threading.Lock()
        self._clock = clock
        self._active = {"h2d": 0, "compute": 0}
        self._last: float | None = None
        self.h2d_s = 0.0
        self.compute_s = 0.0
        self.overlap_s = 0.0

    def _account(self, now: float) -> None:
        if self._last is not None:
            dt = now - self._last
            if dt > 0:
                h = self._active["h2d"] > 0
                c = self._active["compute"] > 0
                if h:
                    self.h2d_s += dt
                if c:
                    self.compute_s += dt
                if h and c:
                    self.overlap_s += dt
        self._last = now

    def enter(self, side: str) -> None:
        with self._lock:
            self._account(self._clock())
            self._active[side] += 1

    def exit(self, side: str) -> None:
        with self._lock:
            self._account(self._clock())
            self._active[side] = max(0, self._active[side] - 1)

    @contextmanager
    def track(self, side: str):
        self.enter(side)
        try:
            yield
        finally:
            self.exit(side)


# ---------------------------------------------------------------------------
# compressed chunk staging + decode-on-device

# wire-plan entry kinds (per streamed column, frozen on first chunk)
_W_RAW = "raw"      # full storage width, zero base
_W_FOR = "for"      # frame-of-reference: narrow deltas + base
_W_RLE = "rle"      # run values (narrow) + run lengths, frozen run cap
_W_BITS = "bits"    # validity bitmap, packbits little-endian

_NARROW = (np.dtype(np.uint8), np.dtype(np.uint16), np.dtype(np.uint32))


def _narrow_for(span: int) -> np.dtype | None:
    for dt in _NARROW:
        if span <= int(np.iinfo(dt).max):
            return dt
    return None


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


@partial(jax.jit, static_argnames=("meta", "cap"))
def _decode_staged(staged, bases, count, *, meta, cap):
    """ONE dispatch expanding a staged (wire-encoded) chunk to full-width
    device columns + the live-row mask. `meta` is the static wire plan:
    a tuple of (key, kind) pairs; shapes are constant across chunks so
    XLA compiles this exactly once per frozen plan."""
    out = {}
    for k, kind in meta:
        if kind == _W_BITS:
            packed = staged[k]
            idx = jnp.arange(cap, dtype=jnp.int32)
            bits = (packed[idx >> 3] >> (idx & 7).astype(jnp.uint8)) & 1
            out[k] = bits != 0
        elif kind == _W_RLE:
            vals, lens = staged[k]
            b = bases[k]
            ends = jnp.cumsum(lens.astype(jnp.int64))
            idx = jnp.searchsorted(
                ends, jnp.arange(cap, dtype=jnp.int64), side="right")
            idx = jnp.clip(idx, 0, vals.shape[0] - 1)
            out[k] = vals[idx].astype(b.dtype) + b
        else:  # raw / for: widen + add base (base is 0 for raw)
            b = bases[k]
            out[k] = staged[k].astype(b.dtype) + b
    sel = jnp.arange(cap, dtype=jnp.int64) < count
    return out, sel


class ChunkStager:
    """Host-side encoder for one streamed table: freezes a per-column
    wire plan on first chunk (cost model: storage/encoding), then turns
    each [start, end) window into a staged tree of wire-encoded arrays
    whose SHAPES are constant across chunks (the decode kernel compiles
    once). `compress=False` pins every column to the raw/FOR baseline —
    the bench A/B lever."""

    def __init__(self, table: Table, cols, cap: int, compress: bool = True):
        self.table = table
        self.cols = tuple(sorted(set(cols)))
        self.cap = int(cap)
        self.compress = compress
        self.sub_schema = Schema(tuple(
            f for f in table.schema.fields if f.name in self.cols))
        # key -> (_W_*, narrow_dtype|None, base, run_cap) frozen entries
        self._plan: dict[str, tuple] = {}

    # -------------------------------------------------------- wire plan
    def _freeze(self, key: str, full: np.ndarray, storage: np.dtype) -> tuple:
        hit = self._plan.get(key)
        if hit is not None:
            return hit
        a = np.asarray(full)
        entry = (_W_RAW, None, 0, 0)
        if np.dtype(storage).kind in "iu" and a.ndim == 1 and len(a):
            st = analyze_ints(a.astype(np.int64, copy=False))
            span = st.vmax - st.vmin
            nt = _narrow_for(span)
            enc = _W_RAW
            if self.compress:
                e, _p = choose_encoding(a.astype(np.int64, copy=False), st)
                if e == ENC_RLE:
                    enc = _W_RLE
                elif e == ENC_FOR and nt is not None and (
                        nt.itemsize < np.dtype(storage).itemsize):
                    enc = _W_FOR
            elif nt is not None and nt.itemsize < np.dtype(storage).itemsize:
                # baseline keeps the pre-existing FOR narrowing (the wire
                # discipline chunked streaming always had)
                enc = _W_FOR
            if enc == _W_RLE and nt is None:
                enc = _W_RAW
            if enc == _W_RLE:
                # frozen run capacity: 2x the table-wide per-chunk run
                # density (a chunk of cap rows holds ~nruns*cap/n runs),
                # clamped to the chunk capacity itself
                n = max(len(a), 1)
                est = int(st.nruns * self.cap / n) + 1
                run_cap = min(_next_pow2(max(2 * est, 16)), self.cap)
                entry = (_W_RLE, nt, st.vmin, run_cap)
            elif enc == _W_FOR:
                entry = (_W_FOR, nt, st.vmin, 0)
        self._plan[key] = entry
        return entry

    # ---------------------------------------------------------- staging
    def stage(self, s: int, e: int):
        """Encode one window. Returns (staged, bases, meta, wire_bytes,
        decoded_bytes): `staged` is the host tree to device_put, `meta`
        the static decode plan for THIS chunk (normally the frozen plan;
        a frame-violating chunk degrades its column to raw)."""
        t = self.table
        cap = self.cap
        staged: dict = {}
        bases: dict = {}
        meta: list[tuple[str, str]] = []
        decoded = 0

        def add_raw(key, a, storage):
            pad = cap - len(a)
            if pad:
                a = np.concatenate(
                    [a, np.zeros((pad,) + a.shape[1:], dtype=a.dtype)])
            staged[key] = np.ascontiguousarray(a, dtype=storage)
            bases[key] = np.dtype(storage).type(0)
            meta.append((key, _W_RAW))

        def add(key, full, storage):
            nonlocal decoded
            a = np.asarray(full[s:e], dtype=storage)
            decoded += cap * np.dtype(storage).itemsize
            kind, nt, base, run_cap = self._freeze(key, full, storage)
            if kind == _W_RLE:
                starts = np.flatnonzero(
                    np.concatenate(([True], a[1:] != a[:-1]))
                ) if len(a) else np.zeros(0, np.int64)
                nruns = len(starts)
                if 0 < nruns <= run_cap:
                    vals = a[starts].astype(np.int64) - base
                    if int(vals.min()) >= 0 and int(vals.max()) <= int(
                            np.iinfo(nt).max):
                        lens = np.diff(
                            np.concatenate((starts, [len(a)]))
                        ).astype(np.int32)
                        vpad = np.zeros(run_cap - nruns, dtype=nt)
                        lpad = np.zeros(run_cap - nruns, dtype=np.int32)
                        staged[key] = (
                            np.concatenate([vals.astype(nt), vpad]),
                            np.concatenate([lens, lpad]),
                        )
                        bases[key] = np.dtype(storage).type(base)
                        meta.append((key, _W_RLE))
                        return
                # run blow-up / frame violation: this chunk ships wide
                add_raw(key, a, storage)
                return
            if kind == _W_FOR:
                d = a.astype(np.int64) - base
                if len(d) == 0 or (int(d.min()) >= 0 and int(d.max())
                                   <= int(np.iinfo(nt).max)):
                    d = d.astype(nt)
                    pad = cap - len(d)
                    if pad:
                        # pad INSIDE the frame (zero delta = table min)
                        d = np.concatenate([d, np.zeros(pad, dtype=nt)])
                    staged[key] = d
                    bases[key] = np.dtype(storage).type(base)
                    meta.append((key, _W_FOR))
                    return
                add_raw(key, a, storage)
                return
            add_raw(key, a, storage)

        for f in self.sub_schema.fields:
            add(f.name, t.data[f.name], f.dtype.storage_np)
        for c, v in t.valid.items():
            if c in self.cols:
                decoded += cap
                bits = np.packbits(
                    np.asarray(v[s:e], np.bool_), bitorder="little")
                nbytes = (cap + 7) >> 3
                if len(bits) < nbytes:
                    # pad rows read as INVALID; sel masks them anyway
                    bits = np.concatenate(
                        [bits, np.zeros(nbytes - len(bits), np.uint8)])
                staged[f"#v:{c}"] = bits
                meta.append((f"#v:{c}", _W_BITS))

        wire = sum(
            (a[0].nbytes + a[1].nbytes) if isinstance(a, tuple) else a.nbytes
            for a in staged.values())
        return staged, bases, tuple(sorted(meta)), wire, decoded

    def decode_batch(self, item: "StagedChunk", cols=None):
        """Decoded-on-device ColumnBatch for a staged chunk (the chunk
        executor's table read for the streamed table). `cols` narrows
        the batch to a requested subset (must be ⊆ the staged set)."""
        from ..core.column import ColumnBatch

        want = self.cols if cols is None else tuple(sorted(set(cols)))
        decoded, sel = _decode_staged(
            item.staged, item.bases, item.count,
            meta=item.meta, cap=self.cap)
        dcols = {k: v for k, v in decoded.items()
                 if not k.startswith("#v:") and k in want}
        dvalid = {k[3:]: v for k, v in decoded.items()
                  if k.startswith("#v:") and k[3:] in want}
        t = self.table
        schema = self.sub_schema if want == self.cols else Schema(tuple(
            f for f in t.schema.fields if f.name in want))
        return ColumnBatch(
            cols=dcols,
            valid=dvalid,
            sel=sel,
            nrows=jnp.sum(sel, dtype=jnp.int64),
            schema=schema,
            dicts={c: d for c, d in t.dicts.items() if c in want},
        )


class StagedChunk:
    """One wire-encoded chunk, device_put in flight: the prefetcher's
    unit of work. Holds the governor staged lease for its host-pinned
    wire buffers; release is idempotent and always reached (drain path
    or prefetcher close)."""

    __slots__ = ("win", "staged", "bases", "meta", "count", "wire_bytes",
                 "decoded_bytes", "lease")

    def __init__(self, win, staged, bases, meta, count, wire_bytes,
                 decoded_bytes, lease):
        self.win = win
        self.staged = staged
        self.bases = bases
        self.meta = meta
        self.count = count
        self.wire_bytes = wire_bytes
        self.decoded_bytes = decoded_bytes
        self.lease = lease

    def release(self) -> None:
        if self.lease is not None:
            self.lease.release()


class ChunkPrefetcher:
    """Stages chunk windows `depth` ahead of the consumer on a small
    thread: host encode + jax.device_put + block_until_ready (the H2D
    side of the overlap meter runs HERE, concurrent with the consumer's
    compute side). The bounded queue is the backpressure: at most
    `depth` staged chunks are in flight, each holding a governor staged
    lease. close() drains and releases everything — the ledger balances
    even when the consumer dies mid-stream."""

    _SENTINEL = object()

    def __init__(self, stager: ChunkStager, windows, depth: int,
                 meter: OverlapMeter, governor=None, tenant: str = "sys"):
        self.stager = stager
        self.windows = list(windows)
        self.depth = max(1, int(depth))
        self.meter = meter
        self.governor = governor
        self.tenant = tenant
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._closed = threading.Event()
        self._exc: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, name="ob-stream-prefetch", daemon=True)
        self._thread.start()

    def _stage_one(self, win) -> StagedChunk:
        s, e = win
        staged, bases, meta, wire, dec = self.stager.stage(s, e)
        lease = None
        if self.governor is not None:
            lease = self.governor.stage(self.tenant, wire)
        try:
            with self.meter.track("h2d"):
                staged = jax.device_put(staged)
                jax.block_until_ready(staged)
        except BaseException:
            if lease is not None:
                lease.release()
            raise
        return StagedChunk(win, staged, bases, meta, e - s, wire, dec, lease)

    def _run(self) -> None:
        try:
            for win in self.windows:
                if self._closed.is_set():
                    return
                item = self._stage_one(win)
                while not self._closed.is_set():
                    try:
                        self._q.put(item, timeout=0.05)
                        break
                    except queue.Full:
                        continue
                else:
                    item.release()
                    return
        except BaseException as exc:  # surfaced at the consumer's get()
            self._exc = exc
        finally:
            while True:
                try:
                    self._q.put(self._SENTINEL, timeout=0.05)
                    break
                except queue.Full:
                    if self._closed.is_set():
                        break

    def get(self) -> StagedChunk | None:
        """Next staged chunk, or None when the stream is exhausted.
        Re-raises a staging error on the consumer thread."""
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._exc is not None and self._q.empty():
                    raise self._exc
                continue
            if item is self._SENTINEL:
                if self._exc is not None:
                    raise self._exc
                return None
            return item

    def restage(self, win) -> StagedChunk:
        """Synchronous re-stage for the rare overflow redispatch path
        (the forward pipeline stays one-directional)."""
        return self._stage_one(win)

    def close(self) -> None:
        """Stop the thread and release every undelivered staged lease.
        Idempotent; called from the consumer's finally so a statement
        error/timeout cannot leak staged bytes."""
        self._closed.set()
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not self._SENTINEL:
                item.release()
        self._thread.join(timeout=5.0)
        # anything the thread pushed between drain and join
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not self._SENTINEL:
                item.release()


# ---------------------------------------------------------------------------
# sizing helpers


def decoded_row_bytes(catalog, table: str, cols) -> int:
    """Per-row DECODED (on-device) bytes of the streamed columns — what
    chunk sizing must budget for. The staged (compressed) host bytes are
    charged separately through the governor's staged ledger; sizing from
    wire bytes would let a high-ratio RLE column overcommit HBM by its
    encoding ratio."""
    t = catalog[table]
    per = 0
    for c in cols:
        if c in t.schema:
            per += t.schema[c].storage_np.itemsize
        if c in t.valid:
            per += 1
    return max(per, 1)


def assemble_partials_table(partial_schema: Schema, cols, valids, dicts,
                            cap: int):
    """Concatenate per-chunk/per-partition partial outputs into the
    padded $partials overlay Table at a grow-only power-of-two capacity
    (the merge executable's input shape — stable across runs). Returns
    (table, new_cap)."""
    data = {k: np.concatenate(v) for k, v in cols.items()}
    vdata = {k: np.concatenate(v) for k, v in valids.items()}
    n_part = len(next(iter(data.values()))) if data else 0
    while cap < n_part:
        cap *= 2
    pad = cap - n_part
    if pad:
        data = {
            k: np.concatenate([v, np.zeros(pad, dtype=v.dtype)])
            for k, v in data.items()
        }
        vdata = {
            k: np.concatenate([v, np.zeros(pad, dtype=np.bool_)])
            for k, v in vdata.items()
        }
    data["$live"] = np.concatenate(
        [np.ones(n_part, np.int8), np.zeros(pad, np.int8)]
    )
    part_fields = [
        Field(f.name,
              f.dtype.with_nullable(f.dtype.nullable or f.name in vdata))
        for f in partial_schema.fields
    ]
    part_fields.append(Field("$live", DataType.int8()))
    table = Table(
        "$partials", Schema(tuple(part_fields)), data,
        {k: d for k, d in dicts.items() if k in data},
        valid=vdata,
    )
    return table, cap


# ---------------------------------------------------------------------------
# the pipelined chunk loop


def run_stream(cp, qparams: tuple = (), max_retries: int = 3):
    """The streaming chunk loop of ChunkedPreparedPlan for single-chip
    chunk sources: prefetch-staged compressed chunks, decode on device,
    dispatch `depth` ahead of the draining fetch, fold partials.

    Returns (cols, valids, dicts) accumulators for the $partials
    assembly. Overflow keeps the params-generation discipline of the
    legacy loop: one bump+recompile per generation, in-flight siblings
    re-dispatch for free on the grown capacities."""
    from collections import deque

    from ..share.interrupt import checkpoint

    ex = cp.executor
    t = ex.catalog[cp.stream.table]
    n = t.nrows or 0
    stats = cp.stream_stats
    meter = OverlapMeter()

    depth = max(0, int(getattr(ex, "stream_prefetch_depth", 2)))
    compress = bool(getattr(ex, "stream_compress", True))
    governor = getattr(ex, "governor", None)
    tenant = getattr(ex, "tenant", "sys")

    windows: deque = deque()
    s = 0
    while s < n:
        e = min(s + cp.chunk_rows, n)
        windows.append((s, e))
        s = e
    if n == 0:
        windows.append((0, 0))

    # the streamed table's columns per the compiled chunk program
    stream_cols: tuple = ()
    for _alias, tname, tcols in cp.chunk_prepared.input_spec:
        if tname == cp.stream.table:
            stream_cols = tcols
            break
    stager = ChunkStager(t, stream_cols, cp.chunk_rows, compress=compress)
    cp.chunk_exec.set_stager(stager)

    # in-flight device residency: decoded chunk + staged wire buffers per
    # pipeline slot; cap the dispatch depth inside the device budget
    # exactly like the legacy loop did for its two slots
    row_b = decoded_row_bytes(ex.catalog, cp.stream.table, stream_cols)
    chunk_bytes = row_b * cp.chunk_rows
    fit = max(1, int(ex.device_budget * 0.5) // max(chunk_bytes, 1))
    dispatch_depth = max(1, min(max(depth, 1) + 1, fit))

    prefetch = ChunkPrefetcher(
        stager, list(windows), depth, meter, governor=governor,
        tenant=tenant) if depth > 0 else None

    pending: deque = deque()  # (item, gen, out, ovf)
    redispatch: deque = deque()  # overflow re-runs (StagedChunk)
    attempts_of: dict = {}
    params_gen = 0
    cols: dict[str, list] = {f.name: [] for f in cp.partial_schema.fields}
    valids: dict[str, list] = {}
    dicts: dict = {}
    drained = 0
    total = len(windows)

    def dispatch(item: StagedChunk):
        ws, we = item.win
        cp.chunk_exec.set_chunk_staged(ws, we, item)
        try:
            with meter.track("compute"):
                out, ovf = cp.chunk_prepared.jitted(
                    cp.chunk_prepared._inputs(), qparams)
        except BaseException:
            # a failed dispatch is the item's last owner: release here or
            # the staged ledger leaks on statement error
            item.release()
            raise
        pending.append((item, params_gen, out, ovf))

    try:
        while drained < total:
            checkpoint()  # a killed query stops between chunks
            while redispatch and len(pending) < dispatch_depth:
                dispatch(redispatch.popleft())
            while (prefetch is not None and len(pending) < dispatch_depth
                   and drained + len(pending) + len(redispatch) < total):
                item = prefetch.get()
                if item is None:
                    break
                windows.popleft()
                dispatch(item)
            if prefetch is None and not pending and windows:
                # prefetch off (A/B baseline): stage synchronously — the
                # wire and the device strictly alternate
                win = windows.popleft()
                ws, we = win
                staged, bases, meta, wire, dec = stager.stage(ws, we)
                lease = governor.stage(tenant, wire) \
                    if governor is not None else None
                try:
                    with meter.track("h2d"):
                        staged = jax.device_put(staged)
                        jax.block_until_ready(staged)
                except BaseException:
                    if lease is not None:
                        lease.release()
                    raise
                dispatch(StagedChunk(win, staged, bases, meta, we - ws,
                                     wire, dec, lease))
            if not pending:
                continue
            item, gen, out, ovf = pending.popleft()
            try:
                fetch_cols = {
                    f.name: out.cols[f.name]
                    for f in cp.partial_schema.fields
                }
                fetch_valid = {
                    k: v for k, v in out.valid.items() if k in fetch_cols
                }
                with meter.track("compute"):
                    hovf, hcols, hvalid, hsel = jax.device_get(
                        (ovf, fetch_cols, fetch_valid, out.sel))
            except BaseException:
                # popped from pending → the finally can no longer see it
                item.release()
                raise
            overflows = cp.chunk_prepared._overflows(np.asarray(hovf))
            if overflows:
                ws, we = item.win
                if gen == params_gen:
                    a = attempts_of.get(ws, 0)
                    if a >= max_retries:
                        raise RuntimeError(
                            f"chunk [{ws},{we}) capacity overflow after "
                            f"{max_retries} retries: {overflows}")
                    attempts_of[ws] = a + 1
                    cp.retries += 1
                    cp.chunk_prepared.retries += 1
                    cp.chunk_prepared.params.bump(overflows)
                    (cp.chunk_prepared.jitted,
                     cp.chunk_prepared.input_spec,
                     cp.chunk_prepared.overflow_nodes) = (
                        cp.chunk_prepared.executor.compile(
                            cp.chunk_prepared.plan,
                            cp.chunk_prepared.params))
                    params_gen += 1
                redispatch.appendleft(item)
                continue
            item.release()
            stats.chunks += 1
            stats.staged_bytes += item.wire_bytes
            stats.decoded_bytes += item.decoded_bytes
            drained += 1
            sel = np.asarray(hsel)
            for f in cp.partial_schema.fields:
                cols[f.name].append(np.asarray(hcols[f.name])[sel])
                v = hvalid.get(f.name)
                if v is not None:
                    valids.setdefault(f.name, []).append(np.asarray(v)[sel])
                elif f.name in valids:
                    valids[f.name].append(
                        np.ones(int(sel.sum()), np.bool_))
            dicts.update(out.dicts)
    finally:
        if prefetch is not None:
            prefetch.close()
        for item, _gen, _out, _ovf in pending:
            item.release()
        for item in redispatch:
            item.release()
        cp.chunk_exec.set_stager(None)
        stats.h2d_s += meter.h2d_s
        stats.compute_s += meter.compute_s
        stats.overlap_s += meter.overlap_s
        # host-tax ledger: a streamed plan's per-chunk walls would
        # otherwise vanish inside the statement's dispatch span — hint
        # the non-overlapped H2D as wall and the chunk compute as device
        # busy onto the current statement's ledger (the window clamp in
        # the serving layer keeps these inside the dispatch wall)
        led = _gap.current()
        if led is not None:
            led.add("h2d", max(0.0, meter.h2d_s - meter.overlap_s))
            led.device(meter.compute_s)

    return cols, valids, dicts


# ---------------------------------------------------------------------------
# grace-hash partitioned join / group-by


class NotPartitionable(Exception):
    """The plan shape does not admit grace-hash partitioning (caller
    falls through to whole-table upload, same contract as
    chunked.NotStreamable)."""


def _path_to_scan(plan, scan):
    path = []

    def find(op) -> bool:
        from .executor import _children

        path.append(op)
        if op is scan:
            return True
        for c in _children(op):
            if find(c):
                return True
        path.pop()
        return False

    if not find(plan):
        raise NotPartitionable("scan not reachable")
    return path


def _streams_down(path, from_pos: int) -> bool:
    """Filter/Project-only (plus probe-side joins) below path[from_pos]."""
    for parent, child in zip(path[from_pos + 1:], path[from_pos + 2:]):
        if isinstance(parent, (Filter, Project)):
            continue
        if isinstance(parent, JoinOp):
            if child is not parent.left:
                return False
            continue
        if isinstance(parent, Scan):
            continue
        return False
    return True


def _resolve_base_col(path_tail, name: str) -> str | None:
    """Trace a column name down a Filter/Project chain to its base-table
    column (None when any hop is a computed expression). `path_tail`
    runs from the chain's top node down to the Scan."""
    cur = name
    for node in path_tail:
        if isinstance(node, Project):
            hit = None
            for out_name, expr in node.exprs:
                if out_name == cur:
                    hit = expr
                    break
            if not isinstance(hit, E.ColRef):
                return None
            cur = hit.name
        elif isinstance(node, Filter):
            continue
        elif isinstance(node, Scan):
            a, _, c = cur.partition(".")
            return c if a == node.alias and c else None
        else:
            return None
    return None


def _live_scan(scan: Scan, overlay_name: str, cols) -> Scan:
    """The scan rewritten onto its overlay partition table: same alias,
    schema narrowed to the partitioned columns plus a `$live` guard whose
    pushed predicate masks the pad rows (one static program serves every
    partition)."""
    live = E.Compare("=", E.ColRef(f"{scan.alias}.$live"), E.lit(1))
    pushed = live if scan.pushed_filter is None else E.BoolOp(
        "and", (scan.pushed_filter, live))
    fields = tuple(
        f for f in scan.schema.fields
        if f.name.split(".", 1)[1] in cols
    ) + (Field(f"{scan.alias}.$live", DataType.int8()),)
    return dc_replace(
        scan, table=overlay_name, schema=Schema(fields),
        pushed_filter=pushed, needed=None)


def _hash_partition(n_parts: int, key: np.ndarray) -> np.ndarray:
    h = (key.astype(np.uint64, copy=False)
         * np.uint64(0x9E3779B97F4A7C15)) >> np.uint64(33)
    return (h % np.uint64(n_parts)).astype(np.int64)


def _spill_partitions(tmp, table: Table, cols, key_col: str,
                      n_parts: int):
    """Hash-partition the needed columns (+ validity) of one table into
    per-partition tmp-file segments (the host 'spill tier'). Returns
    (segments per partition, max partition rows)."""
    key = np.asarray(table.data[key_col]).astype(np.int64, copy=False)
    part = _hash_partition(n_parts, key)
    segs: list[list[str]] = [[] for _ in range(n_parts)]
    names = [c for c in cols if c in table.schema]
    max_rows = 0
    for p in range(n_parts):
        m = part == p
        rows = int(m.sum())
        max_rows = max(max_rows, rows)
        if not rows:
            continue
        seg = {c: np.asarray(table.data[c])[m] for c in names}
        for c, v in table.valid.items():
            if c in cols:
                seg[f"#v:{c}"] = np.asarray(v, np.bool_)[m]
        segs[p].append(tmp.write_segment(seg))
    return segs, max_rows


def derive_partition_count(total_bytes: int, budget: int,
                           governor=None) -> int:
    """Power-of-two partition count sized so one partition PAIR fits
    comfortably on device: target ~budget/4 per partition (two sides +
    decode headroom), clamped to [2, 256]. The governor's remaining
    budget — what is actually free right now — tightens the target."""
    avail = max(int(budget), 1)
    if governor is not None:
        rem = governor.remaining()
        if rem > 0:
            avail = min(avail, rem)
    target = max(avail // 4, 1 << 16)
    p = _next_pow2(max(2, -(-int(total_bytes) // target)))
    return min(p, 256)


class GraceHashPreparedPlan:
    """Out-of-core execution when chunk streaming is NOT enough: the
    build side of a join (or the whole input of a keyed group-by) also
    exceeds the budget. Each grace input hash-partitions by its
    join/group key into host tmp-file segments; ONE static device
    program — the split subtree over fixed-capacity $live-masked overlay
    tables — runs per partition (pair); partials merge through the same
    $partials machinery as chunk streaming.

    mode "join":    partials re-aggregate / pass through exactly as
                    chunked partials do (a group may span partitions).
    mode "groupby": partitioning ON a group key makes groups partition-
                    disjoint, so ANY aggregate — including count
                    distinct — is exact per partition and the merge is
                    pure concatenation.
    """

    def __init__(self, executor, plan, split, kind: str, mode: str,
                 scans: dict[str, tuple[Scan, str, frozenset]],
                 n_parts: int):
        # scans: alias -> (scan node, partition-key base column,
        #                  needed base columns)
        from .chunked import (_merge_plan, _partials_scan, _replace_node,
                              _OverlayCatalog)
        from .executor import Executor

        self.executor = executor
        self.plan = plan
        self.split = split
        self.kind = kind
        self.mode = mode
        self.n_parts = n_parts
        self.retries = 0
        self.stream_stats = StreamStats()
        self._scans = scans

        if mode == "groupby":
            # per-partition output is FINAL for its groups: the merge is
            # the rename projection (passthrough shape) regardless of
            # what the aggregate computes
            out_s = output_schema(split)
            pscan = _partials_scan(out_s)
            merge_node = Project(
                pscan,
                tuple((f.name, E.ColRef(f"$m.{f.name}"))
                      for f in out_s.fields),
            )
            part_plan = split
            self.above_plan = _replace_node(plan, split, merge_node)
            self.partial_schema = out_s
        else:
            part_plan, _scan, merge_node = _merge_plan(split, kind)
            self.above_plan = _replace_node(plan, split, merge_node)
            self.partial_schema = output_schema(split)

        # rewrite every partitioned scan onto its overlay table
        self._overlay_names = {}
        for alias, (scan, _key, cols) in scans.items():
            oname = f"$gh_{alias}"
            self._overlay_names[alias] = oname
            part_plan = _replace_node(
                part_plan, scan, _live_scan(scan, oname, cols))
        self.part_plan = part_plan

        # per-partition executor over the overlay catalog: chunking off
        # (partitions are already bounded), whole-table premises off
        # (partition rows are permuted slices)
        self._overlay_extra: dict = {}
        self.part_exec = Executor(
            _OverlayCatalog(executor.catalog, self._overlay_extra),
            unique_keys={}, stats=None,
        )
        self.part_exec.chunking_enabled = False
        self.part_exec.clustered_agg_enabled = False
        self.part_exec.scan_slice_enabled = False
        self._part_prepared = None
        self._out_dicts: dict = {}

        self.merge_exec = Executor(
            _OverlayCatalog(executor.catalog, self._overlay_extra),
            unique_keys=executor.unique_keys, stats=None,
        )
        self.merge_exec.chunking_enabled = False
        self._partial_cap = 1024
        self._merge_prepared = None
        self._merge_cap = 0

    # ------------------------------------------------------------- run
    def run_nocheck(self, qparams: tuple = ()):
        return self.run(qparams=qparams)

    def _overlay_for(self, alias: str, scan: Scan, cols, segs, tmp,
                     cap: int) -> Table:
        """One partition of one grace input as a padded overlay Table."""
        t = self.executor.catalog[scan.table]
        names = [c for c in sorted(cols) if c in t.schema]
        parts = [tmp.read_segment(path) for path in segs]
        if parts:
            data = {c: np.concatenate([p[c] for p in parts])
                    for c in names}
            vdata = {
                c: np.concatenate([p[f"#v:{c}"] for p in parts])
                for c in t.valid if c in cols
            }
        else:
            data = {c: np.zeros(0, dtype=t.schema[c].storage_np)
                    for c in names}
            vdata = {c: np.zeros(0, np.bool_)
                     for c in t.valid if c in cols}
        n = len(next(iter(data.values()))) if data else 0
        pad = cap - n
        if pad:
            data = {
                c: np.concatenate([v, np.zeros(pad, dtype=v.dtype)])
                for c, v in data.items()
            }
            vdata = {
                c: np.concatenate([v, np.zeros(pad, np.bool_)])
                for c, v in vdata.items()
            }
        data["$live"] = np.concatenate(
            [np.ones(n, np.int8), np.zeros(pad, np.int8)])
        fields = [f for f in t.schema.fields if f.name in data]
        fields.append(Field("$live", DataType.int8()))
        return Table(
            self._overlay_names[alias], Schema(tuple(fields)), data,
            {c: d for c, d in t.dicts.items() if c in data}, valid=vdata,
        )

    def run(self, max_retries: int = 3, qparams: tuple = ()):
        from ..share.interrupt import checkpoint
        from ..storage.tmp_file import TmpFileManager

        stats = self.stream_stats
        cols: dict[str, list] = {
            f.name: [] for f in self.partial_schema.fields}
        valids: dict[str, list] = {}
        with TmpFileManager(
                tenant=getattr(self.executor, "tenant", "sys"),
                metrics=getattr(self.executor, "metrics", None)) as tmp:
            # phase 1: co-partition every grace input by its key column;
            # the fixed per-input capacity (max partition, pow2) is what
            # lets ONE compiled program serve all partitions
            segs: dict[str, list[list[str]]] = {}
            caps: dict[str, int] = {}
            for alias, (scan, key_col, pcols) in self._scans.items():
                t = self.executor.catalog[scan.table]
                segs[alias], mx = _spill_partitions(
                    tmp, t, pcols, key_col, self.n_parts)
                caps[alias] = _next_pow2(max(mx, 16))
                checkpoint()
            stats.spill_partitions += self.n_parts

            # phase 2: one static program over every partition (pair)
            for p in range(self.n_parts):
                checkpoint()
                for alias, (scan, _k, pcols) in self._scans.items():
                    oname = self._overlay_names[alias]
                    self._overlay_extra[oname] = self._overlay_for(
                        alias, scan, pcols, segs[alias][p], tmp,
                        caps[alias])
                    self.part_exec.invalidate_table(oname)
                if self._part_prepared is None:
                    self._part_prepared = self.part_exec.prepare(
                        self.part_plan)
                hcols, hvalid, hsel = self._run_partition(
                    max_retries, qparams)
                sel = np.asarray(hsel)
                for f in self.partial_schema.fields:
                    cols[f.name].append(np.asarray(hcols[f.name])[sel])
                    v = hvalid.get(f.name)
                    if v is not None:
                        valids.setdefault(f.name, []).append(
                            np.asarray(v)[sel])
                    elif f.name in valids:
                        valids[f.name].append(
                            np.ones(int(sel.sum()), np.bool_))
                for alias in segs:
                    for path in segs[alias][p]:
                        tmp.free_segment(path)

        partials, self._partial_cap = assemble_partials_table(
            self.partial_schema, cols, valids, dict(self._out_dicts),
            self._partial_cap)
        self._overlay_extra["$partials"] = partials
        self.merge_exec.invalidate_table("$partials")
        if self._merge_prepared is None or \
                self._merge_cap != self._partial_cap:
            self._merge_prepared = self.merge_exec.prepare(self.above_plan)
            self._merge_cap = self._partial_cap
        return self._merge_prepared.run(max_retries, qparams=qparams)

    def _run_partition(self, max_retries: int, qparams: tuple):
        prepared = self._part_prepared
        for attempt in range(max_retries + 1):
            out, ovf_vec = prepared.jit_call(prepared._inputs(), qparams)
            fetch_cols = {
                f.name: out.cols[f.name]
                for f in self.partial_schema.fields
            }
            fetch_valid = {
                k: v for k, v in out.valid.items() if k in fetch_cols
            }
            hovf, hcols, hvalid, hsel = jax.device_get(
                (ovf_vec, fetch_cols, fetch_valid, out.sel))
            overflows = prepared._overflows(np.asarray(hovf))
            if not overflows:
                self._out_dicts.update(out.dicts)
                return hcols, hvalid, hsel
            if attempt == max_retries:
                raise RuntimeError(
                    f"grace partition overflow after {max_retries} "
                    f"retries: {overflows}")
            self.retries += 1
            prepared.retries += 1
            prepared.params.bump(overflows)
            prepared.recompile()
        raise AssertionError


def try_grace_hash(executor, plan, budget: int):
    """Entry hook from Executor.prepare's `except NotStreamable` branch:
    find a grace-hash-partitionable shape or raise NotPartitionable.

    join mode:    the two biggest scans both exceed the budget, they meet
                  at a JoinOp whose probe path streams and whose build
                  chain is Filter/Project-only, and one equi-key pair
                  resolves to base integer columns on both sides.
    groupby mode: one over-budget input under a keyed Aggregate whose
                  path streams and one group key resolves to a base
                  integer column (then ANY aggregate — incl. distinct —
                  is exact per partition).
    """
    from .chunked import _MERGE_FN, _row_bytes, scan_bytes

    needed = executor._needed_columns(plan)
    scans = executor._collect_scans(plan)
    if not scans:
        raise NotPartitionable("no scans")
    sizes = sorted(
        ((scan_bytes(executor.catalog, s, needed), s) for s in scans),
        key=lambda p: -p[0])

    def single_scan(s: Scan):
        if sum(1 for x in scans if x.table == s.table) > 1:
            raise NotPartitionable(
                "partitioned table scanned more than once")

    def needed_cols(s: Scan, key_col: str) -> frozenset:
        t = executor.catalog[s.table]
        base = needed.get(s.alias) or {t.schema.fields[0].name}
        return frozenset(set(base) | {key_col})

    big_bytes, big = sizes[0]
    single_scan(big)
    path = _path_to_scan(plan, big)
    gov = getattr(executor, "governor", None)

    def lowest(pred):
        best = None
        for i, node in enumerate(path):
            if pred(node):
                best = i
        return best

    # ---- join mode: second scan also over budget --------------------
    if len(sizes) > 1 and sizes[1][0] > budget:
        build_bytes, build = sizes[1]
        single_scan(build)
        if sum(b for b, _ in sizes[2:]) > budget:
            raise NotPartitionable("three or more over-budget inputs")
        # the JoinOp on the probe path whose RIGHT subtree holds `build`
        join_i = None
        for i, node in enumerate(path):
            if isinstance(node, JoinOp) and path[i + 1] is node.left:
                if any(sc is build
                       for sc in executor._collect_scans(node.right)):
                    join_i = i
                    break
        if join_i is None:
            raise NotPartitionable(
                "no probe-side join over the build scan")
        join = path[join_i]
        if join.kind not in ("inner", "left", "semi", "anti"):
            raise NotPartitionable(f"{join.kind} join not partitionable")
        build_path = _path_to_scan(join.right, build)
        if not all(isinstance(nd, (Filter, Project, Scan))
                   for nd in build_path):
            raise NotPartitionable("build chain not Filter/Project-only")
        # an equi-key pair resolving to base integer columns both sides
        probe_col = build_col = None
        for lk, rk in zip(join.left_keys, join.right_keys):
            if not (isinstance(lk, E.ColRef) and isinstance(rk, E.ColRef)):
                continue
            pc = _resolve_base_col(path[join_i + 1:], lk.name)
            bc = _resolve_base_col(build_path, rk.name)
            if pc is None or bc is None:
                continue
            t1 = executor.catalog[big.table]
            t2 = executor.catalog[build.table]
            if pc in t1.schema and bc in t2.schema \
                    and t1.schema[pc].storage_np.kind in "iu" \
                    and t2.schema[bc].storage_np.kind in "iu":
                probe_col, build_col = pc, bc
                break
        if probe_col is None:
            raise NotPartitionable("no base-resolvable equi-key pair")
        # the split above the join: lowest mergeable aggregate, else the
        # join itself as a passthrough split (budget-guarded partials)
        split_i = kind = None
        i = lowest(lambda nd: isinstance(nd, Aggregate))
        if i is not None and i < join_i and _streams_down(path, i) \
                and not path[i].grouping_sets and all(
                    not d and fn in _MERGE_FN
                    for _nm, fn, _a, d in path[i].aggs):
            split_i, kind = i, "agg"
        if split_i is None:
            if not _streams_down(path, join_i):
                raise NotPartitionable(
                    "no mergeable split above the join")
            est = executor._est_rows(join)
            if est * _row_bytes(output_schema(join)) > budget:
                raise NotPartitionable(
                    "passthrough partials exceed budget")
            split_i, kind = join_i, "passthrough"
        split = path[split_i]
        n_parts = derive_partition_count(
            big_bytes + build_bytes, budget, gov)
        return GraceHashPreparedPlan(
            executor, plan, split, kind, "join",
            {big.alias: (big, probe_col,
                         needed_cols(big, probe_col)),
             build.alias: (build, build_col,
                           needed_cols(build, build_col))},
            n_parts)

    # ---- groupby mode: one big input, keyed aggregate ---------------
    if sum(b for b, _ in sizes[1:]) > budget:
        raise NotPartitionable("multiple over-budget inputs, no join")
    i = lowest(lambda nd: isinstance(nd, Aggregate))
    if i is None or not path[i].group_keys or not _streams_down(path, i):
        raise NotPartitionable("no keyed aggregate over the big scan")
    agg = path[i]
    if agg.grouping_sets is not None:
        raise NotPartitionable("grouping sets span partitions")
    key_col = None
    for _name, e in agg.group_keys:
        if not isinstance(e, E.ColRef):
            continue
        c = _resolve_base_col(path[i + 1:], e.name)
        if c is None:
            continue
        t = executor.catalog[big.table]
        if c in t.schema and t.schema[c].storage_np.kind in "iu":
            key_col = c
            break
    if key_col is None:
        raise NotPartitionable("no base-resolvable group key")
    n_parts = derive_partition_count(big_bytes, budget, gov)
    return GraceHashPreparedPlan(
        executor, plan, agg, "agg", "groupby",
        {big.alias: (big, key_col, needed_cols(big, key_col))}, n_parts)


__all__ = [
    "StreamStats", "OverlapMeter", "ChunkStager", "StagedChunk",
    "ChunkPrefetcher", "run_stream", "decoded_row_bytes",
    "assemble_partials_table", "GraceHashPreparedPlan", "try_grace_hash",
    "NotPartitionable", "derive_partition_count",
]
