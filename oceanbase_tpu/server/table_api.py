"""OBKV: the NoSQL table API over tablets.

Reference surface: observer/table + src/libtable — a key-value/HBase-style
API (get/put/delete/batch/scan with filters) that reaches tablets through
the same transaction and storage stack as SQL, without the SQL compiler.

The rebuild's TableApi binds one table: point ops run as single-statement
transactions through TransService (fully transactional, replicated);
scans read a leader MVCC snapshot with optional key-range pruning and a
row filter. Values are python dicts keyed by column name; VARCHAR cells
are strings (codes stay internal)."""

from __future__ import annotations

import numpy as np

from ..core.dtypes import TypeKind
from ..storage import OP_DELETE, OP_PUT
from .database import Database, SqlError, _OpenTx


class TableApi:
    def __init__(self, db: Database, table: str):
        self.db = db
        ti = db.tables.get(table)
        if ti is None:
            raise SqlError(f"no such table {table}")
        self.table = table

    @property
    def _ti(self):
        return self.db.tables[self.table]

    # ------------------------------------------------------------ encode
    def _coerce_row(self, row: dict) -> tuple:
        ti = self._ti
        from .database import _coerce

        vals = []
        for f in ti.schema.fields:
            if f.name not in row:
                raise SqlError(f"missing column {f.name}")
            vals.append(_coerce(row[f.name], f.dtype,
                                ti.dicts.get(f.name), f.name))
        return tuple(vals)

    def _decode_row(self, vals: tuple) -> dict:
        ti = self._ti
        out = {}
        for f, v in zip(ti.schema.fields, vals):
            if f.dtype.kind is TypeKind.VARCHAR:
                out[f.name] = ti.dicts[f.name].decode_one(int(v))
            elif f.dtype.is_decimal:
                out[f.name] = float(v) / f.dtype.decimal_factor
            else:
                out[f.name] = v if not isinstance(v, np.generic) else v.item()
        return out

    def _key_of(self, row_or_key) -> tuple:
        ti = self._ti
        if isinstance(row_or_key, dict):
            return tuple(
                int(self._coerce_row(row_or_key)[ti.schema.index(k)])
                for k in ti.key_cols
            )
        k = row_or_key if isinstance(row_or_key, tuple) else (row_or_key,)
        return tuple(int(x) for x in k)

    # --------------------------------------------------------------- ops
    def _tx_op(self, muts: list[tuple[tuple, int, tuple | None]]) -> None:
        """One autocommit tx staging the given mutations (batch = atomic).
        Secondary indexes are maintained in the same tx: puts are upserts,
        so the OLD row is read first to tombstone superseded entries."""
        ti = self._ti
        tx = _OpenTx(self.db)
        from ..tx.tablelock import LockMode
        from .database import DbSession

        try:
            self.db.lock_mgr.lock(tx.ctx.tx_id, ti.tablet_id, LockMode.ROW_X)
            routed = [
                (*ti.partition_for_key(key), key, op, vals)
                for key, op, vals in muts
            ]
            needed_ls = {ls for ls, _t, _k, _o, _v in routed}
            if ti.indexes:
                needed_ls.add(ti.ls_id)
            for ls in sorted(needed_ls):
                tx.ensure_leader(ls)
            index_muts: list[tuple[int, tuple, int, tuple | None]] = []
            if ti.indexes:
                for ls_id, tab_id, key, op, vals in routed:
                    old = tx.svc.replicas[ls_id].tablets[tab_id].get(
                        key, tx.ctx.read_snapshot, tx_id=tx.ctx.tx_id
                    )
                    rep = tx.svc.replicas[ti.ls_id]
                    for idx in ti.indexes.values():
                        old_ik = (
                            DbSession._index_entry(ti, idx, old[1])[0]
                            if old is not None else None
                        )
                        if op == OP_DELETE:
                            if old_ik is not None:
                                index_muts.append(
                                    (idx.tablet_id, old_ik, OP_DELETE, None))
                            continue
                        new_ik, new_iv = DbSession._index_entry(ti, idx, vals)
                        if old_ik == new_ik:
                            continue
                        if idx.unique:
                            hit = rep.tablets[idx.tablet_id].get(
                                new_ik, tx.ctx.read_snapshot,
                                tx_id=tx.ctx.tx_id)
                            if hit is not None:
                                raise SqlError(
                                    f"unique index {idx.name} violation on "
                                    f"{new_ik}")
                        if old_ik is not None:
                            index_muts.append(
                                (idx.tablet_id, old_ik, OP_DELETE, None))
                        index_muts.append(
                            (idx.tablet_id, new_ik, OP_PUT, new_iv))
            for ls_id, tab_id, key, op, vals in routed:
                tx.svc.write(tx.ctx, ls_id, tab_id, key, op, vals)
            for tab_id, key, op, vals in index_muts:
                tx.svc.write(tx.ctx, ti.ls_id, tab_id, key, op, vals)
            self.db.cluster.commit_sync(tx.svc, tx.ctx)
            ti.data_version += 1
        except Exception:
            if not tx.ctx.is_done:
                tx.svc.abort(tx.ctx)
            raise
        finally:
            self.db.lock_mgr.release_all(tx.ctx.tx_id)
            ti.cached_data_version = -1

    def put(self, row: dict) -> None:
        """Upsert one row (HBase-put semantics: blind write)."""
        vals = self._coerce_row(row)
        self._tx_op([(self._key_of(row), OP_PUT, vals)])

    def batch_put(self, rows: list[dict]) -> int:
        muts = [(self._key_of(r), OP_PUT, self._coerce_row(r)) for r in rows]
        self._tx_op(muts)
        return len(muts)

    def delete(self, key) -> None:
        self._tx_op([(self._key_of(key), OP_DELETE, None)])

    def get(self, key) -> dict | None:
        ti = self._ti
        k = self._key_of(key)
        ls_id, tab_id = ti.partition_for_key(k)
        rep = self.db._leader_replica_ls(ls_id)
        hit = rep.tablets[tab_id].get(k, self.db.cluster.gts.current())
        return None if hit is None else self._decode_row(hit[1])

    def scan(self, key_min=None, key_max=None, row_filter=None,
             limit: int | None = None) -> list[dict]:
        """Range scan on the FIRST key column with optional row filter
        (the HBase-filter analog, applied host-side post-snapshot)."""
        ti = self._ti
        ranges = None
        if key_min is not None or key_max is not None:
            lo = -float("inf") if key_min is None else float(key_min)
            hi = float("inf") if key_max is None else float(key_max)
            ranges = {ti.key_cols[0]: (lo, hi)}
        snap = self.db.cluster.gts.current()
        parts = []
        for pls, ptab in ti.all_partitions():
            rep = self.db._leader_replica_ls(pls)
            parts.append(rep.tablets[ptab].scan(snap, ranges=ranges))
        data = (
            parts[0] if len(parts) == 1
            else {c: np.concatenate([p[c] for p in parts]) for c in parts[0]}
        )
        names = ti.schema.names()
        n = len(data[names[0]]) if names else 0
        if ranges is not None and n:
            # zone-map pruning is block-approximate: apply the exact bound
            k = data[ti.key_cols[0]]
            m = np.ones(n, dtype=bool)
            if key_min is not None:
                m &= k >= key_min
            if key_max is not None:
                m &= k <= key_max
            data = {c: v[m] for c, v in data.items()}
            n = int(m.sum())
        out = []
        for i in range(n):
            row = self._decode_row(tuple(data[c][i] for c in names))
            if row_filter is not None and not row_filter(row):
                continue
            out.append(row)
            if limit is not None and len(out) >= limit:
                break
        return out
